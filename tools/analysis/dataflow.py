"""Project dataflow layer on top of the CFG builder.

Three facilities the rule suites share:

``ModuleFunctions``
    Per-module call resolution: ``self._helper()`` to the enclosing
    class's method, ``helper()`` to a module-level def.  This is the
    boundary the first-order rules of PR 3 could not see across —
    deadlocks and leaked taint are interprocedural facts.  Resolution
    stays *within one module* on purpose: per-file findings must depend
    only on that file's content, or the incremental cache (core.py)
    would go stale silently.  Cross-module facts (the global
    lock-acquisition graph) travel through the project-rule facts
    channel instead.

``LockModel`` / ``lock_facts``
    Which expressions are locks (``self._lock = threading.Lock()``
    attributes, module-level ``_lock = threading.Lock()`` globals,
    function-local locks) and a forward lock-set analysis over a CFG:
    ``with``-acquisition adds the token at ``WITH_ENTER``, every exit
    path releases it at the duplicated ``WITH_EXIT`` — so exceptional
    paths release correctly, matching ``with`` semantics.

``traced_closure``
    Bounded (two-level) interprocedural taint for the trace rules: a
    traced function's taint crosses ``self._helper(x)`` / ``helper(x)``
    call boundaries into the callee's matching parameters.  Two levels
    is enough for this tree (helpers of helpers), and the bound keeps
    the analyzer's runtime linear in practice.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .cfg import (BRANCH, CFG, LOOP, STMT, WITH_ENTER, WITH_EXIT, Node,
                  build_cfg, forward, node_exprs)
from .core import assigned_names, last_component

#: how many call levels interprocedural walks descend (the ISSUE's
#: "bounded, two-level inlining is enough for this tree")
INLINE_DEPTH = 2

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _self_attr(node) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


# --------------------------------------------------------------------------
# call resolution
# --------------------------------------------------------------------------

class ModuleFunctions:
    """Function/method tables for one parsed module."""

    def __init__(self, tree: ast.Module):
        self.module_defs: Dict[str, ast.FunctionDef] = {}
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        self.owner: Dict[int, str] = {}   # id(fn) -> class name
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                self.module_defs[node.name] = node
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.methods[(node.name, item.name)] = item
                        self.owner[id(item)] = node.name

    def class_of(self, fn) -> Optional[str]:
        return self.owner.get(id(fn))

    def resolve_call(self, caller, call: ast.Call):
        """The same-module FunctionDef a call dispatches to, or None.
        ``self.X()`` resolves within the caller's class; a bare name
        resolves to a module-level def."""
        attr = _self_attr(call.func)
        if attr is not None:
            cls = self.class_of(caller)
            if cls is not None:
                target = self.methods.get((cls, attr))
                if isinstance(target, ast.FunctionDef):
                    return target
            return None
        if isinstance(call.func, ast.Name):
            target = self.module_defs.get(call.func.id)
            if target is not None and target is not caller:
                return target
        return None


def iter_scope_nodes(root) -> Iterable[ast.AST]:
    """Nodes lexically in ``root``'s own scope: the canonical pruned
    walk every rule shares.  Nested function/lambda/class BODIES are
    skipped — they are separate scopes with their own analyses, and
    their code does not execute where it is defined.  The root itself
    is expanded regardless of its type (so ``iter_scope_nodes(fn)``
    walks the function's body) and is yielded first."""
    stack = [root]
    while stack:
        node = stack.pop()
        if node is not root and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def iter_calls(root) -> Iterable[ast.Call]:
    """Calls lexically inside ``root``'s own scope (pruned walk)."""
    for node in iter_scope_nodes(root):
        if isinstance(node, ast.Call):
            yield node


def bind_args(fn: ast.FunctionDef, call: ast.Call,
              flagged) -> Set[str]:
    """Parameter names of ``fn`` that receive a *flagged* argument at
    this call site.  ``flagged(expr) -> bool``.  ``self`` receivers are
    skipped for method calls."""
    args = fn.args
    params = [a.arg for a in args.posonlyargs + args.args]
    offset = 1 if params[:1] == ["self"] and _self_attr(call.func) else 0
    out: Set[str] = set()
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            if flagged(a.value) and args.vararg is not None:
                out.add(args.vararg.arg)
            continue
        idx = i + offset
        if flagged(a):
            if idx < len(params):
                out.add(params[idx])
            elif args.vararg is not None:
                out.add(args.vararg.arg)
    kw_ok = {a.arg for a in args.args + args.posonlyargs + args.kwonlyargs}
    for k in call.keywords:
        if not flagged(k.value):
            continue
        if k.arg is None or k.arg not in kw_ok:
            if args.kwarg is not None:
                out.add(args.kwarg.arg)
        else:
            out.add(k.arg)
    return out - {"self"}


# --------------------------------------------------------------------------
# lock discovery + lock-set analysis
# --------------------------------------------------------------------------

class LockModel:
    """Lock-valued names of one module.

    Tokens are stable, human-meaningful identities used in findings and
    in the global acquisition graph, QUALIFIED by the file (normally
    the relpath) — two classes both named ``Worker`` in different files
    hold different locks, and an unqualified token would conflate them
    into false deadlock cycles:

    - ``<qual>:ClassName._lock`` for ``self._lock = threading.Lock()``
    - ``<qual>:_lock`` for a module-level ``_lock = threading.Lock()``
    - ``<qual>:fn.<name>`` for a function-local lock (rare; still
      ordered)
    """

    def __init__(self, tree: ast.Module, qualifier: str):
        self.qualifier = qualifier.replace("\\", "/")
        self.class_locks: Dict[str, Set[str]] = {}
        self.module_locks: Set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and last_component(node.value.func) in _LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                attrs = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) \
                            and isinstance(sub.value, ast.Call) \
                            and last_component(sub.value.func) in _LOCK_CTORS:
                        for t in sub.targets:
                            a = _self_attr(t)
                            if a is not None:
                                attrs.add(a)
                if attrs:
                    self.class_locks[node.name] = attrs
        # anywhere at all — including function locals, which the maps
        # above don't cover (sweeps use this as their cheap gate)
        self.has_locks = bool(self.module_locks or self.class_locks) \
            or any(isinstance(n, ast.Assign)
                   and isinstance(n.value, ast.Call)
                   and last_component(n.value.func) in _LOCK_CTORS
                   for n in ast.walk(tree))

    def _local_locks(self, fn) -> Set[str]:
        out = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and last_component(node.value.func) in _LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def tokens_for_expr(self, expr, fn, cls: Optional[str],
                        local_locks: Optional[Set[str]] = None):
        """Lock token for one ``with`` context expression (or None).
        Accepts the bare lock and ``lock.acquire_timeout(...)``-style
        helper calls on it."""
        if isinstance(expr, ast.Call) and isinstance(expr.func,
                                                     ast.Attribute):
            return self.tokens_for_expr(expr.func.value, fn, cls,
                                        local_locks)
        attr = _self_attr(expr)
        if attr is not None and cls is not None \
                and attr in self.class_locks.get(cls, ()):
            return f"{self.qualifier}:{cls}.{attr}"
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return f"{self.qualifier}:{expr.id}"
            if local_locks is not None and expr.id in local_locks:
                return f"{self.qualifier}:" \
                       f"{getattr(fn, 'name', '<module>')}.{expr.id}"
        return None

    def with_token_list(self, with_stmt, fn, cls,
                        local_locks=None) -> List[str]:
        """Lock tokens of one ``with`` statement IN ACQUISITION ORDER —
        Python enters the items left to right, so ``with a, b:`` is an
        ordering fact (a before b), not just a set."""
        out = []
        for item in with_stmt.items:
            tok = self.tokens_for_expr(item.context_expr, fn, cls,
                                       local_locks)
            if tok is not None:
                out.append(tok)
        return out

    def with_tokens(self, with_stmt, fn, cls, local_locks=None) -> Set[str]:
        return set(self.with_token_list(with_stmt, fn, cls, local_locks))


def acquire_tokens(fact: frozenset, toks) -> frozenset:
    """Add one nesting LEVEL of each token: facts are ``(token,
    level)`` pairs so reentrant ``with self._lock:`` blocks (RLock)
    balance — the inner exit must not release the outer hold."""
    out = set(fact)
    for t in toks:
        n = max((lvl for tk, lvl in out if tk == t), default=0)
        out.add((t, n + 1))
    return frozenset(out)


def release_tokens(fact: frozenset, toks) -> frozenset:
    out = set(fact)
    for t in toks:
        lvls = [lvl for tk, lvl in out if tk == t]
        if lvls:
            out.discard((t, max(lvls)))
    return frozenset(out)


def held_names(fact) -> frozenset:
    """Plain token set from a leveled lock fact (None stays None)."""
    if fact is None:
        return None
    return frozenset(t for t, _lvl in fact)


def lock_facts(cfg: CFG, locks: LockModel, fn, cls,
               entry: frozenset = frozenset(), must: bool = False):
    """``{id(node): fact at node ENTRY}`` where a fact is a frozenset
    of ``(token, nesting level)`` pairs — ``held_names`` flattens one
    to the token set.  Levels make reentrant acquisition of the same
    lock balance correctly on exit.

    ``entry`` is a plain token set (callers pass the lock set a callee
    inherits); ``must=False`` (union merge) answers "may this lock be
    held here" (what blocking-under-lock wants); ``must=True``
    (intersection) answers "is it guaranteed held" (what the thread
    rule wants).
    """
    local = locks._local_locks(fn) if isinstance(fn, ast.FunctionDef) \
        else None

    def transfer(node: Node, fact):
        if node.kind == WITH_ENTER:
            return acquire_tokens(
                fact, locks.with_tokens(node.stmt, fn, cls, local))
        if node.kind == WITH_EXIT:
            return release_tokens(
                fact, locks.with_tokens(node.stmt, fn, cls, local))
        return fact

    join = (lambda a, b: a & b) if must else (lambda a, b: a | b)
    return forward(cfg, frozenset((t, 1) for t in entry), transfer,
                   join)


# --------------------------------------------------------------------------
# bounded interprocedural walks
# --------------------------------------------------------------------------

def walk_with_locks(mod_tree, locks: LockModel, funcs: ModuleFunctions,
                    fn, visit, entry=frozenset(), chain=(),
                    depth=INLINE_DEPTH, _seen=None):
    """Drive ``visit(fn, node, held, chain)`` over every CFG node of
    ``fn`` with its entry lock-set ``entry``, then descend (bounded)
    into same-module callees reached while locks are held — a helper
    called under ``with self._lock`` runs under that lock too.

    ``chain`` is the call path (for messages).  Returns nothing;
    ``visit`` accumulates.
    """
    if _seen is None:
        _seen = set()
    key = (id(fn), entry)
    if key in _seen:
        return
    _seen.add(key)
    cfg = build_cfg(fn)
    if cfg is None:          # async def etc.: not analyzed, never guessed
        return
    cls = funcs.class_of(fn)
    facts = lock_facts(cfg, locks, fn, cls, entry=entry)
    for node in cfg.nodes():
        held = held_names(facts.get(id(node)))
        if held is None:
            continue
        # for WITH_ENTER the fact is the set held BEFORE acquiring —
        # exactly what the lock-order edge wants
        visit(fn, node, held, chain)
        if depth <= 0 or not held:
            continue
        if node.kind not in (STMT, BRANCH, LOOP, WITH_ENTER):
            continue
        for expr in node_exprs(node):
            for call in _calls_of_stmt(expr):
                callee = funcs.resolve_call(fn, call)
                if callee is None:
                    continue
                walk_with_locks(mod_tree, locks, funcs, callee, visit,
                                entry=held,
                                chain=chain + (getattr(fn, "name", "?"),),
                                depth=depth - 1, _seen=_seen)


def _calls_of_stmt(stmt) -> List[ast.Call]:
    """Calls that execute AT this statement.  A nested def/class
    statement executes none of its body here — defining is not calling
    — and a lambda's body runs at its later call site, never where the
    lambda literal appears (``Thread(target=lambda: q.get())`` does not
    block the constructing thread)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return list(iter_calls(stmt))


# --------------------------------------------------------------------------
# SPMD axis-binding facts (spmd_rules)
# --------------------------------------------------------------------------
#
# A ``shard_map``-wrapped body runs one program per device, and its
# collectives (``lax.psum(x, "dp")``) are only meaningful for axes the
# enclosing mesh defines.  These helpers answer, statically, "which axis
# names does this shard_map call bind, and do we know ALL of them?" —
# the *closed* bit is what keeps the spmd rules sound: when any spec or
# the mesh is not literal-resolvable the binding is OPEN and the rules
# must not claim an axis is unbound.

_PSPEC_NAMES = {"PartitionSpec", "P"}


def scope_assignments(scope, module_tree=None) -> Dict[str, ast.AST]:
    """``name -> value expr`` for SINGLE simple assignments lexically in
    ``scope`` (module-level assignments as fallback).  A name assigned
    twice is dropped — two bindings means we cannot know which one a
    later read sees without flow analysis, and these facts feed
    soundness-critical "is the axis set closed" decisions."""
    out: Dict[str, ast.AST] = {}
    dead: Set[str] = set()

    def scan(root):
        for node in iter_scope_nodes(root):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name in out or name in dead:
                    dead.add(name)
                    out.pop(name, None)
                else:
                    out[name] = node.value

    if module_tree is not None:
        scan(module_tree)
    if scope is not None and scope is not module_tree:
        # any name the function binds OTHER than via a recorded simple
        # assignment shadows a same-named module-level literal: its
        # value is a runtime fact, so the module entry must die — a
        # parameter named ``mesh`` must not resolve to the module's
        # ``mesh = make_mesh(...)``
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = scope.args
            for p in (a.posonlyargs + a.args + a.kwonlyargs
                      + ([a.vararg] if a.vararg else [])
                      + ([a.kwarg] if a.kwarg else [])):
                dead.add(p.arg)
                out.pop(p.arg, None)
        for node in iter_scope_nodes(scope):
            bound = None
            if isinstance(node, ast.Assign):
                # the single-Name form is scan()'s own (recorded)
                # territory; every OTHER shape — tuple unpacking
                # (``mesh, opt = ...``), multi-target — still binds
                if not (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    bound = set()
                    for t in node.targets:
                        bound |= assigned_names(t)
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                                   ast.NamedExpr)):
                bound = assigned_names(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor,
                                   ast.comprehension)):
                bound = assigned_names(node.target)
            elif isinstance(node, ast.withitem) \
                    and node.optional_vars is not None:
                bound = assigned_names(node.optional_vars)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                bound = {a.asname or a.name.split(".")[0]
                         for a in node.names}
            elif isinstance(node, ast.ExceptHandler) \
                    and node.name is not None:
                bound = {node.name}
            elif isinstance(node, ast.Delete):
                bound = set()
                for t in node.targets:
                    bound |= assigned_names(t)
            if bound:
                for name in bound:
                    dead.add(name)
                    out.pop(name, None)
        # nested def/class statements bind their NAME in this scope but
        # iter_scope_nodes prunes the nodes themselves — a dedicated
        # shallow walk (bodies not expanded) catches the shadow
        stack = [scope]
        while stack:
            node = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    dead.add(child.name)
                    out.pop(child.name, None)
                    continue
                if isinstance(child, ast.Lambda):
                    continue
                stack.append(child)
        scan(scope)
    return out


def resolve_spec_axes(expr, assigns: Dict[str, ast.AST],
                      depth: int = 3) -> Tuple[Set[str], bool]:
    """``(axis names, closed)`` for one in_specs/out_specs expression.
    ``closed=True`` means every axis the spec could name is in the set
    (all literals resolved); any unresolvable subexpression — a
    computed spec, a parameter, ``tree_map(...)`` — makes it open."""
    if depth <= 0:
        return set(), False
    if isinstance(expr, ast.Constant):
        if expr.value is None:
            return set(), True
        if isinstance(expr.value, str):
            return {expr.value}, True
        return set(), True
    if isinstance(expr, (ast.Tuple, ast.List)):
        axes: Set[str] = set()
        closed = True
        for el in expr.elts:
            el = el.value if isinstance(el, ast.Starred) else el
            a, c = resolve_spec_axes(el, assigns, depth)
            axes |= a
            closed &= c
        return axes, closed
    if isinstance(expr, ast.Call) and \
            last_component(expr.func) in _PSPEC_NAMES:
        axes, closed = set(), True
        for a in list(expr.args) + [k.value for k in expr.keywords]:
            sub_axes, sub_closed = resolve_spec_axes(a, assigns, depth)
            axes |= sub_axes
            closed &= sub_closed
        return axes, closed
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        la, lc = resolve_spec_axes(expr.left, assigns, depth)
        ra, rc = resolve_spec_axes(expr.right, assigns, depth)
        return la | ra, lc and rc
    if isinstance(expr, ast.Name) and expr.id in assigns:
        return resolve_spec_axes(assigns[expr.id], assigns, depth - 1)
    return set(), False


def resolve_mesh_axes(expr, assigns: Dict[str, ast.AST],
                      depth: int = 3) -> Tuple[Set[str], bool]:
    """``(axis names, closed)`` for a ``mesh=`` expression: literal
    ``make_mesh(dp=2, tp=-1)`` kwargs or ``Mesh(devs, ("dp", "tp"))``
    axis-name literals.  A mesh arriving through a variable/attribute
    (``mesh=self.mesh``) is open — its axes are a runtime fact the
    ``parallel.mesh.shard_map`` wrapper validates instead."""
    if depth <= 0:
        return set(), False
    if isinstance(expr, ast.Name) and expr.id in assigns:
        return resolve_mesh_axes(assigns[expr.id], assigns, depth - 1)
    if not isinstance(expr, ast.Call):
        return set(), False
    callee = last_component(expr.func)
    if callee == "make_mesh":
        if expr.args:         # axes dict / devices positionally: give up
            return set(), False
        if any(k.arg is None for k in expr.keywords):   # **kwargs splat
            return set(), False
        axes = {k.arg for k in expr.keywords
                if k.arg not in ("devices", "axes")}
        for k in expr.keywords:
            if k.arg != "axes":
                continue
            # axes= dict form: literal str keys resolve, anything else
            # (a variable, computed keys) makes the binding OPEN
            if isinstance(k.value, ast.Constant) and k.value.value is None:
                continue
            if isinstance(k.value, ast.Dict) and all(
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    for key in k.value.keys):
                axes |= {key.value for key in k.value.keys}
            else:
                return set(), False
        if not axes:
            # the documented no-axis default: every device on one 'dp'
            return {"dp"}, True
        return axes, True
    if callee in ("Mesh", "AbstractMesh"):
        names_expr = None
        if len(expr.args) >= 2:
            names_expr = expr.args[1]
        for k in expr.keywords:
            if k.arg == "axis_names":
                names_expr = k.value
        if names_expr is None:
            return set(), False
        if isinstance(names_expr, ast.Constant) \
                and isinstance(names_expr.value, str):
            return {names_expr.value}, True
        if isinstance(names_expr, (ast.Tuple, ast.List)) \
                and all(isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                        for e in names_expr.elts):
            return {e.value for e in names_expr.elts}, True
    return set(), False


def traced_closure(funcs: ModuleFunctions, fn, taint0: Set[str],
                   compute_taint, effective_taint,
                   depth=INLINE_DEPTH):
    """(function, taint set, chain) triples: the traced function itself
    plus every same-module callee a tainted value flows into, to the
    inlining bound.  ``compute_taint(fn, seed)`` closes a seed set over
    assignments; ``effective_taint(expr, taint)`` is the value-taint
    test (both live in trace_rules — this keeps the engine rule-free).
    """
    out = []
    seen = set()

    def visit(f, taint, chain, d):
        key = (id(f), frozenset(taint))
        if key in seen:
            return
        seen.add(key)
        out.append((f, taint, chain))
        if d <= 0:
            return
        for call in iter_calls(f):
            callee = funcs.resolve_call(f, call)
            if callee is None or isinstance(callee, ast.AsyncFunctionDef):
                continue
            params = bind_args(callee, call,
                               lambda e: bool(effective_taint(e, taint)))
            if not params:
                continue
            visit(callee, compute_taint(callee, seed=params),
                  chain + (f.name,), d - 1)

    visit(fn, taint0, (), depth)
    return out


