"""Concurrency rules hosted on the CFG/dataflow engine.

The hot path of this tree is genuinely concurrent — PrefetchingIter
producer threads, the DevicePrefetcher, the serving DynamicBatcher's
batch thread, GracefulExit signal latches — and they coordinate through
locks, bounded queues and events.  Three hazard classes there are
*interprocedural path* properties no first-order AST walk can see:

``blocking-under-lock``
    A lock held across an unbounded blocking operation — ``Queue.get``/
    ``put`` without a timeout, ``Thread.join()``, ``Event.wait()``,
    ``lock.acquire()``, ``time.sleep``/``retry_call`` (it sleeps), a
    device transfer (``device_put``/``block_until_ready``), or a
    ``fault.fire()`` injection point (an armed fault raises — and
    ``fire`` itself takes the fault registry's lock, so firing under a
    local lock nests lock acquisition into every production call site).
    One stalled consumer then wedges every thread that needs the lock.
    The walk follows ``self.``-helper and module-level calls two levels
    deep: a helper called under ``with self._lock`` runs under that
    lock too.

``lock-order-inversion``
    The project-wide lock-acquisition graph (built from every
    ``with <lock>`` site, including those reached through helper calls
    while a lock is held) contains a cycle: somewhere A is taken then
    B, somewhere else B then A.  Each order is locally fine; together
    they deadlock under the right interleaving.  This is a project
    rule: the two sites are usually in different files (batcher admit
    lock vs. server stats lock vs. profiler counter lock).

``signal-handler-unsafe``
    A function installed via ``signal.signal(...)`` (GracefulExit's
    latch handler pattern) that acquires a lock, blocks, performs
    reentrancy-unsafe I/O (``print``/``open``), or raises anything
    other than ``KeyboardInterrupt``/``SystemExit``.  A Python signal
    handler runs on the main thread at an arbitrary bytecode boundary:
    if the interrupted frame holds the lock the handler wants, the
    process deadlocks; an unexpected exception surfaces at whatever
    line happened to be executing.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .cfg import BRANCH, LOOP, STMT, WITH_ENTER, build_cfg, node_exprs
from .core import Finding, ProjectRule, Rule, dotted_name, last_component
from .dataflow import (INLINE_DEPTH, LockModel, ModuleFunctions,
                       _calls_of_stmt, _self_attr, iter_calls,
                       walk_with_locks)

_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
                "JoinableQueue"}
_THREAD_CTORS = {"Thread"}
_EVENT_CTORS = {"Event"}
_SLEEPERS = {"sleep", "retry_call"}
_DEVICE_CALLS = {"device_put", "block_until_ready"}


# --------------------------------------------------------------------------
# light receiver typing (queues / threads / events)
# --------------------------------------------------------------------------

class ChannelTypes:
    """attr/name -> 'queue' | 'thread' | 'thread_list' | 'event', per
    class and per function, from constructor assignments (the same
    convention thread_rules uses: types are what ``__init__`` built)."""

    def __init__(self, tree: ast.Module):
        self.class_attrs: Dict[str, Dict[str, str]] = {}
        self.module_names: Dict[str, str] = {}
        for node in tree.body:
            kind = self._ctor_kind(node)
            if kind:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_names[t.id] = kind
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                attrs: Dict[str, str] = {}
                for sub in ast.walk(node):
                    kind = self._ctor_kind(sub)
                    if kind:
                        for t in sub.targets:
                            a = _self_attr(t)
                            if a is not None:
                                attrs[a] = kind
                    elif isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "append" \
                            and sub.args \
                            and isinstance(sub.args[0], ast.Call) \
                            and last_component(sub.args[0].func) \
                            in _THREAD_CTORS:
                        a = _self_attr(sub.func.value)
                        if a is not None:
                            attrs[a] = "thread_list"
                if attrs:
                    self.class_attrs[node.name] = attrs

    @staticmethod
    def _ctor_kind(node) -> Optional[str]:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            return None
        ctor = last_component(node.value.func)
        if ctor in _QUEUE_CTORS:
            return "queue"
        if ctor in _THREAD_CTORS:
            return "thread"
        if ctor in _EVENT_CTORS:
            return "event"
        return None

    def locals_of(self, fn, cls=None) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for node in ast.walk(fn):
            kind = self._ctor_kind(node)
            if kind:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = kind
        # `for t in self._threads:` — the loop variable of a
        # thread-container is a thread (``cls`` is needed to resolve
        # the ``self._threads`` container attribute)
        for node in ast.walk(fn):
            if isinstance(node, ast.For) \
                    and isinstance(node.target, ast.Name):
                if self._kind_of(node.iter, fn, cls, out) == "thread_list":
                    out[node.target.id] = "thread"
        return out

    def _kind_of(self, expr, fn, cls, local) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and cls is not None:
            return self.class_attrs.get(cls, {}).get(attr)
        if isinstance(expr, ast.Name):
            if local and expr.id in local:
                return local[expr.id]
            return self.module_names.get(expr.id)
        return None

    def kind_of(self, expr, fn, cls, local=None) -> Optional[str]:
        if local is None:
            local = self.locals_of(fn, cls)
        return self._kind_of(expr, fn, cls, local)


def _has_timeout(call: ast.Call, tpos=None) -> bool:
    """Is this blocking call bounded?  A non-None ``timeout=`` keyword;
    or, when the method takes the timeout positionally at index
    ``tpos`` (``get(block, timeout)`` → 1, ``put(item, block,
    timeout)`` → 2, ``acquire(blocking, timeout)`` → 1), a non-None
    positional in that slot; or a literal ``False`` in the BLOCK-FLAG
    slot just before it / a ``block=False`` keyword (non-blocking).
    Only those slots are inspected — ``q.put(False)`` enqueues the
    VALUE False and blocks like any other put."""
    for k in call.keywords:
        if k.arg == "timeout" and not (isinstance(k.value, ast.Constant)
                                       and k.value.value is None):
            return True
        if k.arg in ("block", "blocking") \
                and isinstance(k.value, ast.Constant) \
                and k.value.value is False:
            return True
    if tpos is None:
        return False
    if len(call.args) > tpos \
            and not (isinstance(call.args[tpos], ast.Constant)
                     and call.args[tpos].value is None):
        return True
    flag = tpos - 1
    return len(call.args) > flag \
        and isinstance(call.args[flag], ast.Constant) \
        and call.args[flag].value is False


def blocking_ops(exprs, types: ChannelTypes, locks: LockModel, fn, cls,
                 local_types=None,
                 local_locks=None) -> List[Tuple[ast.AST, str]]:
    """(ast node, human description) for every unbounded blocking (or
    fault-point) operation in the given expressions."""
    if local_locks is None and isinstance(fn, ast.FunctionDef):
        local_locks = locks._local_locks(fn)
    out: List[Tuple[ast.AST, str]] = []
    for expr in exprs:
        for call in _calls_of_stmt(expr):
            func = call.func
            name = last_component(func)
            if isinstance(func, ast.Attribute):
                kind = types.kind_of(func.value, fn, cls, local_types)
                if func.attr in ("get", "put") and kind == "queue" \
                        and not _has_timeout(
                            call, tpos=1 if func.attr == "get" else 2):
                    out.append((call, f"Queue.{func.attr}() without a "
                                      f"timeout"))
                    continue
                if func.attr == "join" and kind in ("thread",
                                                    "thread_list") \
                        and not call.args and not call.keywords:
                    out.append((call, "Thread.join() with no timeout"))
                    continue
                if func.attr == "wait" and kind == "event" \
                        and not call.args and not _has_timeout(call):
                    out.append((call, "Event.wait() with no timeout"))
                    continue
                if func.attr == "acquire" \
                        and locks.tokens_for_expr(func.value, fn, cls,
                                                  local_locks) \
                        and not _has_timeout(call, tpos=1):
                    out.append((call, "lock.acquire() (nested blocking "
                                      "acquisition)"))
                    continue
            if name in _DEVICE_CALLS:
                out.append((call, f"device transfer {name}()"))
            elif name in _SLEEPERS:
                d = dotted_name(func) or name
                if name == "sleep" and d not in ("time.sleep", "sleep"):
                    continue   # foo.sleep() on an unknown object
                out.append((call, f"{d}() (sleeps on this thread)"))
            elif name == "fire" and call.args \
                    and isinstance(call.args[0], ast.Constant):
                out.append((call, f"fault point fire("
                                  f"{call.args[0].value!r}) (an armed "
                                  f"fault raises here; fire() also takes "
                                  f"the fault-registry lock)"))
    return out


def _function_surface(tree: ast.Module):
    """(fn, owning class name) for every module-level def and method."""
    out = []
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            out.append((node, None))
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    out.append((item, node.name))
    return out


# --------------------------------------------------------------------------
# the shared lock sweep: ONE interprocedural walk per module
# --------------------------------------------------------------------------

def _lock_sweep(mod):
    """(blocking findings' raw material, acquisition edges) of one
    module, from a single ``walk_with_locks`` sweep over every function
    — memoized on the ModuleInfo, because the bounded interprocedural
    walk is the most expensive analysis in the suite and both
    ``blocking-under-lock`` and ``lock-order-inversion`` consume it.
    """
    cached = getattr(mod, "_mxlint_lock_sweep", None)
    if cached is not None:
        return cached
    locks = LockModel(mod.tree, mod.relpath)
    blocked: List[tuple] = []   # (op node, why, held, chain, fn name)
    edges: List[list] = []      # [held, acquired, line, fn name]
    if locks.has_locks:       # incl. function-local locks
        funcs = ModuleFunctions(mod.tree)
        types = ChannelTypes(mod.tree)
        local_types: Dict[int, Dict[str, str]] = {}
        local_locks: Dict[int, set] = {}

        def visit(fn, node, held, chain):
            if node.kind not in (STMT, BRANCH, LOOP, WITH_ENTER):
                return
            cls = funcs.class_of(fn)
            if id(fn) not in local_types:
                local_types[id(fn)] = types.locals_of(fn, cls)
                local_locks[id(fn)] = locks._local_locks(fn) \
                    if isinstance(fn, ast.FunctionDef) else set()
            fname = getattr(fn, "name", "?")
            if node.kind == WITH_ENTER:
                ordered = locks.with_token_list(node.stmt, fn, cls,
                                                local_locks[id(fn)])
                for tok in ordered:
                    for h in held:
                        if h != tok:
                            edges.append([h, tok, node.lineno, fname])
                # `with a, b:` acquires left to right — an ordering
                # fact in its own right, even with nothing held
                for i, a in enumerate(ordered):
                    for b in ordered[i + 1:]:
                        if a != b:
                            edges.append([a, b, node.lineno, fname])
            if not held:
                return
            for op, why in blocking_ops(node_exprs(node), types, locks,
                                        fn, cls, local_types[id(fn)],
                                        local_locks[id(fn)]):
                blocked.append((op, why, held, chain, fname))

        for fn, _cls in _function_surface(mod.tree):
            walk_with_locks(mod.tree, locks, funcs, fn, visit)
    result = (blocked, edges)
    try:
        mod._mxlint_lock_sweep = result
    except Exception:
        pass                    # memo is an optimization, never a need
    return result


# --------------------------------------------------------------------------
# blocking-under-lock
# --------------------------------------------------------------------------

class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    description = ("unbounded blocking operation (queue get/put, join, "
                   "wait, sleep, device transfer, fault point) while "
                   "holding a lock")

    def check_module(self, mod):
        for op, why, held, chain, fname in _lock_sweep(mod)[0]:
            via = f" (reached via {' -> '.join(chain)}" \
                  f" -> {fname})" if chain else ""
            yield self.finding(
                mod, op,
                f"{why} while holding {sorted(held)}{via}: one "
                f"stalled thread wedges every thread that needs the "
                f"lock — move the blocking call outside the lock or "
                f"bound it with a timeout")


# --------------------------------------------------------------------------
# lock-order-inversion (project rule: cross-file acquisition graph)
# --------------------------------------------------------------------------

class LockOrderRule(ProjectRule):
    id = "lock-order-inversion"
    description = ("cycle in the global lock-acquisition order graph "
                   "(deadlock under the right interleaving)")

    def facts(self, mod):
        """Directed acquisition edges this file contributes:
        ``[held_token, acquired_token, line, function]``."""
        return _lock_sweep(mod)[1]

    def check_facts(self, facts, root, analyzed):
        graph: Dict[str, Set[str]] = {}
        sites: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        for relpath, edges in facts:
            for held, acq, line, fname in edges or ():
                graph.setdefault(held, set()).add(acq)
                graph.setdefault(acq, set())
                sites.setdefault((held, acq), []).append(
                    (relpath, line, fname))
        for comp in self._cyclic_sccs(graph):
            comp_set = set(comp)
            # every edge INSIDE a cyclic SCC lies on some cycle (an SCC
            # property) — report each of its sites, never a synthetic
            # ordering of the component (for 3+ locks the sorted order
            # is generally not a real cycle and would match no edges)
            intra = [(a, b) for a in comp
                     for b in sorted(graph.get(a, ()))
                     if b in comp_set]
            for a, b in intra:
                for relpath, line, fname in sites.get((a, b), ()):
                    if relpath not in analyzed:
                        continue
                    others = "; ".join(
                        f"{x}->{y} at {s[0]}:{s[1]} ({s[2]})"
                        for x, y in intra if (x, y) != (a, b)
                        for s in sites.get((x, y), ())[:1])
                    yield Finding(
                        rule=self.id, path=relpath, line=line, col=1,
                        message=f"lock order inversion: acquiring '{b}' "
                                f"while holding '{a}' is part of an "
                                f"acquisition cycle among "
                                f"{{{', '.join(comp)}}} ({others}) — "
                                f"two threads taking these locks in "
                                f"opposite orders deadlock; pick one "
                                f"global order")

    @staticmethod
    def _cyclic_sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
        """Strongly-connected components containing a cycle (>1 node,
        or a self-loop), sorted for deterministic output."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        def strongconnect(v):
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in sorted(graph.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or v in graph.get(v, ()):
                    out.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return out


# --------------------------------------------------------------------------
# signal-handler-unsafe
# --------------------------------------------------------------------------

_HANDLER_SAFE_RAISES = {"KeyboardInterrupt", "SystemExit"}
_UNSAFE_IO = {"print", "open"}


class SignalHandlerRule(Rule):
    id = "signal-handler-unsafe"
    description = ("signal handler (or a helper it calls) acquires a "
                   "lock, blocks, does reentrancy-unsafe I/O, or raises "
                   "a non-exit exception")

    def check_module(self, mod):
        funcs = ModuleFunctions(mod.tree)
        handlers = self._handlers(mod.tree, funcs)
        if not handlers:
            return
        locks = LockModel(mod.tree, mod.relpath)
        types = ChannelTypes(mod.tree)
        seen: Set[int] = set()
        for handler in handlers:
            yield from self._check_handler(mod, handler, funcs, locks,
                                           types, handler.name, (),
                                           INLINE_DEPTH, seen)

    @staticmethod
    def _handlers(tree, funcs: ModuleFunctions):
        """FunctionDefs registered via ``signal.signal(sig, h)``."""
        out = []
        for node in ast.walk(tree):
            cls = None
            if isinstance(node, ast.ClassDef):
                cls = node.name
                calls = [c for m in node.body
                         if isinstance(m, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))
                         for c in ast.walk(m) if isinstance(c, ast.Call)]
            elif isinstance(node, ast.Module):
                calls = [c for c in ast.walk(node)
                         if isinstance(c, ast.Call)]
            else:
                continue
            for call in calls:
                if last_component(call.func) != "signal" \
                        or len(call.args) < 2:
                    continue
                target = call.args[1]
                attr = _self_attr(target)
                fn = None
                if attr is not None and cls is not None:
                    fn = funcs.methods.get((cls, attr))
                elif isinstance(target, ast.Name):
                    fn = funcs.module_defs.get(target.id)
                if isinstance(fn, ast.FunctionDef) \
                        and not any(f is fn for f in out):
                    out.append(fn)
        return out

    def _check_handler(self, mod, fn, funcs, locks, types, root_name,
                       chain, depth, seen):
        if id(fn) in seen:
            return
        seen.add(id(fn))
        cfg = build_cfg(fn)
        if cfg is None:      # async handler: not analyzed, skip cleanly
            return
        cls = funcs.class_of(fn)
        local_types = types.locals_of(fn, cls)
        local_locks = locks._local_locks(fn)   # hoisted: one walk per fn
        via = f" (via {' -> '.join(chain)})" if chain else ""
        prefix = f"signal handler '{root_name}'{via}"
        for node in cfg.nodes():
            if node.kind == WITH_ENTER:
                toks = locks.with_tokens(
                    node.stmt, fn, cls, local_locks)
                if toks:
                    yield self.finding(
                        mod, node.stmt,
                        f"{prefix} acquires {sorted(toks)}: it runs on "
                        f"the main thread at an arbitrary bytecode "
                        f"boundary — if the interrupted frame holds the "
                        f"lock, the process deadlocks.  Set a flag/"
                        f"Event and do the work outside the handler")
            exprs = node_exprs(node)
            for op, why in blocking_ops(exprs, types, locks, fn, cls,
                                        local_types, local_locks):
                yield self.finding(
                    mod, op,
                    f"{prefix} performs {why}: a handler must never "
                    f"block — latch state and return")
            for expr in exprs:
                for call in _calls_of_stmt(expr):
                    if isinstance(call.func, ast.Name) \
                            and call.func.id in _UNSAFE_IO:
                        yield self.finding(
                            mod, call,
                            f"{prefix} calls {call.func.id}(): I/O from "
                            f"a signal handler can re-enter whatever "
                            f"stream operation it interrupted — latch "
                            f"and report outside the handler")
            if isinstance(node.stmt, ast.Raise) and node.kind == STMT \
                    and node.stmt.exc is not None:
                raised = last_component(
                    node.stmt.exc.func
                    if isinstance(node.stmt.exc, ast.Call)
                    else node.stmt.exc)
                if raised not in _HANDLER_SAFE_RAISES:
                    yield self.finding(
                        mod, node.stmt,
                        f"{prefix} raises {raised}: the exception "
                        f"surfaces at whatever line the signal "
                        f"interrupted, far from any handling — only "
                        f"KeyboardInterrupt/SystemExit are "
                        f"conventional from handlers")
        if depth > 0:
            for call in iter_calls(fn):
                callee = funcs.resolve_call(fn, call)
                if callee is not None \
                        and isinstance(callee, ast.FunctionDef):
                    yield from self._check_handler(
                        mod, callee, funcs, locks, types, root_name,
                        chain + (fn.name,), depth - 1, seen)
