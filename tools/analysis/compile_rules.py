"""Compile-boundary rules: the jit-construction discipline costguard's
budgets depend on.

A budget golden pins how many executables a surface compiles
(``tools/costguard``) — but only if compilation happens where the
census can see it: at module scope, in a cached/bucketed slot, or in an
explicit warmup.  Two shapes silently break that:

``jit-in-loop``            ``jax.jit(...)`` (or the AOT
                           ``.lower(...).compile(...)`` chain)
                           constructed inside a loop, or the
                           per-request form ``jax.jit(fn)(x)`` inside a
                           function body.  The executable cache hangs
                           off the *wrapper object*, so every fresh
                           wrapper is a fresh trace+compile — tens of
                           seconds of availability loss per request on
                           a big model, the exact failure mode the
                           serving bucket grid exists to kill.
``unbudgeted-entrypoint``  a ``costguard.entrypoint("name")``
                           registration missing either committed gate
                           golden — the cost budget under
                           ``tests/goldens/budgets/`` or the hloguard
                           structural census under
                           ``tests/goldens/hloguard/``.  A surface
                           declared auditable but never actually
                           audited regresses invisibly.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set

from .core import Finding, ProjectRule, Rule, dotted_name, last_component
from .dataflow import iter_scope_nodes


def _jit_aliases(tree: ast.Module) -> Set[str]:
    """Local names that ARE ``jax.jit`` (``from jax import jit [as j]``)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "jit":
                    out.add(a.asname or a.name)
    return out


def _is_jit_ctor(call: ast.Call, aliases: Set[str]) -> bool:
    f = call.func
    if dotted_name(f) == "jax.jit":
        return True
    if isinstance(f, ast.Name) and f.id in aliases:
        return True
    # functools.partial(jax.jit, static_argnums=...)
    if last_component(f) == "partial" and call.args \
            and dotted_name(call.args[0]) == "jax.jit":
        return True
    return False


def _is_aot_chain(call: ast.Call) -> bool:
    """``<expr>.lower(...).compile(...)`` — the AOT idiom.  Anchored on
    the ``.compile`` whose receiver is a ``.lower(...)`` call, so
    ``re.compile`` and ``str.lower`` alone never match."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "compile"
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Attribute)
            and f.value.func.attr == "lower")


def _is_aot_lower(call: ast.Call) -> bool:
    """A ``.lower(avals...)`` call WITH arguments: ``str.lower()`` never
    takes any, jax's AOT ``Wrapped.lower(*args)`` always does."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "lower"
            and bool(call.args or call.keywords))


class JitInLoopRule(Rule):
    id = "jit-in-loop"
    default_severity = "error"
    description = ("jax.jit / lower().compile() constructed inside a loop "
                   "or per-request path (fresh XLA compile every pass)")

    # ------------------------------------------------------------------
    def check_module(self, mod) -> Iterable[Finding]:
        """Only FUNCTION scopes are checked: module-scope loops and
        comprehensions execute once per import, so building a bounded
        registry of wrappers there (`{n: jax.jit(f) for ...}`) is the
        bind-once pattern this rule's fix advice prescribes, not a
        recompile hazard."""
        aliases = _jit_aliases(mod.tree)
        fns = [n for n in ast.walk(mod.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in fns:
            for node in iter_scope_nodes(fn):
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    yield from self._check_loop(mod, node, aliases)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    yield from self._check_comp(mod, node, aliases)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Call) \
                        and _is_jit_ctor(node.func, aliases):
                    yield self.finding(
                        mod, node,
                        "jax.jit(fn)(...) inside a function compiles "
                        "fresh on EVERY call — the executable cache "
                        "hangs off the wrapper object; bind the jitted "
                        "callable once (module scope, or a cached "
                        "attribute like executor's _jit_cache) and call "
                        "that")

    # ------------------------------------------------------------------
    def _flag_ctors(self, mod, roots, aliases, where):
        for root in roots:
            for node in iter_scope_nodes(root):
                if not isinstance(node, ast.Call):
                    continue
                if _is_jit_ctor(node, aliases):
                    yield self.finding(
                        mod, node,
                        f"jax.jit constructed inside {where} — every "
                        f"pass pays a fresh trace+compile (the cache is "
                        f"per-wrapper); hoist the construction out, or "
                        f"key a bounded cache the way the serving "
                        f"bucket grid does")
                elif _is_aot_chain(node) or _is_aot_lower(node):
                    yield self.finding(
                        mod, node,
                        f"AOT lower/compile inside {where} — compile "
                        f"once outside and reuse the executable (budget "
                        f"audits go through tools/costguard's report "
                        f"cache for exactly this reason)")

    def _check_loop(self, mod, loop, aliases):
        # body only: an `else:` clause runs at most once per loop
        # statement, not per iteration — constructing there is fine
        roots = list(loop.body)
        if isinstance(loop, ast.While):
            roots.append(loop.test)      # re-evaluated every iteration
        yield from self._flag_ctors(mod, roots, aliases, "a loop")

    def _check_comp(self, mod, comp, aliases):
        roots = []
        if isinstance(comp, ast.DictComp):
            roots += [comp.key, comp.value]
        else:
            roots.append(comp.elt)
        for gen in comp.generators:
            roots.extend(gen.ifs)
        yield from self._flag_ctors(mod, roots, aliases,
                                    "a comprehension")


class UnbudgetedEntrypointRule(ProjectRule):
    id = "unbudgeted-entrypoint"
    default_severity = "error"
    description = ("costguard entry-point registration with no committed "
                   "budget golden in tests/goldens/budgets/ or no "
                   "structural golden in tests/goldens/hloguard/")

    def facts(self, mod):
        regs = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and last_component(node.func) in ("entrypoint",
                                                      "register_entrypoint") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                regs.append([node.args[0].value, node.lineno])
        return regs or None

    def check_facts(self, facts, root, analyzed):
        # a registered entry point owes BOTH gate goldens: the costguard
        # budget AND the hloguard structural census — either one missing
        # means an unaudited surface
        wanted = (
            ("budgets", "python tests/goldens/budgets/regen_budgets.py"),
            ("hloguard",
             "python tests/goldens/hloguard/regen_hloguard.py"),
        )
        committed = {}
        for subdir, _ in wanted:
            gdir = root / "tests" / "goldens" / subdir
            committed[subdir] = {p.stem for p in gdir.glob("*.json")} \
                if gdir.is_dir() else set()
        for relpath, regs in facts:
            if relpath not in analyzed:
                continue
            for name, line in regs or ():
                missing = [(subdir, regen) for subdir, regen in wanted
                           if name not in committed[subdir]]
                if not missing:
                    continue
                paths = ", ".join(f"tests/goldens/{s}/{name}.json"
                                  for s, _ in missing)
                regens = "; ".join(f"{r} {name}" for _, r in missing)
                yield Finding(
                    rule=self.id, path=relpath, line=line, col=1,
                    message=f"entry point '{name}' is registered but "
                            f"missing gate golden(s): {paths} — commit "
                            f"them ({regens}) or drop the registration")
