"""Incremental cache for mxlint (``.mxlint_cache/``).

One JSON record per (file content, rule set, engine version): the
per-file findings, the suppression table, and every project rule's
facts — everything ``core.analyze`` needs, so a fully-cached run never
parses a single source file.  That is what makes the tier-1 full-tree
gate O(changed files) instead of O(tree) as the CFG/dataflow suite
grows (and what ``tools/chaos_check.py --mode lint`` asserts: the warm
run is ≥5x faster and byte-identical in findings).

Layout: ONE record per source file, named by ``sha256(relpath)`` and
overwritten in place — the cache is bounded by the number of files the
tree has ever had, not by how many revisions each went through (the
tier-1 gate runs warm on every pytest invocation; an append-only
layout would grow a long-lived checkout without bound).  Validity is
checked INSIDE the record: it stores the content key
``sha256(signature || relpath || bytes)`` — where ``signature`` embeds
``core.ENGINE_VERSION``, the Python minor version (AST shapes differ),
and the sorted rule ids — and a mismatch is a miss.  Any analyzer
change that should invalidate every record is a one-line
``ENGINE_VERSION`` bump.  The relpath is part of the content key
because records carry path-anchored findings: two identical files at
different paths must not share one.

Writes are atomic (tmp + ``os.replace``) and best-effort: a read-only
checkout or a lost race degrades to a cache miss, never to an error —
the analyzer must stay runnable anywhere the tree is.
"""
from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Optional

CACHE_DIR_NAME = ".mxlint_cache"
_CK = "_content_key"


class FileCache:
    def __init__(self, root: Path, directory=None, signature: str = ""):
        self.dir = Path(directory) if directory else \
            Path(root) / CACHE_DIR_NAME
        self.signature = signature
        self.hits = 0
        self.misses = 0

    def key(self, relpath: str, data: bytes) -> str:
        h = hashlib.sha256()
        h.update(self.signature.encode("utf-8"))
        h.update(b"\x00")
        h.update(relpath.encode("utf-8"))
        h.update(b"\x00")
        h.update(data)
        return h.hexdigest()[:32]

    def _name(self, relpath: str) -> str:
        return hashlib.sha256(
            relpath.encode("utf-8")).hexdigest()[:32] + ".json"

    def get(self, relpath: str, key: str) -> Optional[dict]:
        try:
            with open(self.dir / self._name(relpath),
                      encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            self.misses += 1
            return None
        if rec.get(_CK) != key:
            self.misses += 1       # stale revision / other rule set
            return None
        self.hits += 1
        return rec

    def put(self, relpath: str, key: str, record: dict):
        try:
            record = dict(record)
            record[_CK] = key
            self.dir.mkdir(parents=True, exist_ok=True)
            name = self._name(relpath)
            tmp = self.dir / f".{name}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(record, f)
            os.replace(tmp, self.dir / name)
        except OSError:
            pass      # best-effort: a miss next run, never a failure
