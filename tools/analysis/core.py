"""mxlint rule engine.

The analysis counterpart of the runtime's fault harness: where
``mx.fault`` makes concurrency/preemption failures *repeatable*, mxlint
makes the invariants that PREVENT them *mechanical*.  TensorFlow's
production experience (PAPERS.md: Abadi et al.) is that large dataflow
frameworks survive on invariant checking in CI, not review; the
whole-program-compile stacks (Julia→TPU, PAPERS.md) show that
trace/compile-boundary discipline is the correctness frontier.  This
engine walks Python sources with ``ast`` (no imports, no execution — it
must be runnable on a broken tree) and applies per-file and
whole-project rules.

Since the CFG/dataflow upgrade the engine has three layers:

- **per-file rules** (``Rule.check_module``) — including the CFG-hosted
  concurrency/lifecycle suite.  Their findings depend ONLY on the one
  file's content, which is what makes the incremental cache sound.
- **project rules** (``ProjectRule``) — cross-file invariants.  Each
  extracts a small serializable *facts* record per file
  (``ProjectRule.facts``) and judges the union
  (``ProjectRule.check_facts``): the op-registry table, the docs symbol
  index, the global lock-acquisition graph.  Facts ride in the same
  cache records as findings, so a fully-cached run never parses a file.
- **the cache** (``.mxlint_cache/``) — per-file JSON records keyed by a
  hash of (engine version, rule set, relative path, file bytes).  See
  ``cache.py``.  ``analyze(use_cache=True)`` opts in; the tier-1 gate
  does, which is how the full-tree gate stays inside its wall-time
  budget as the rule suite grows.

Suppression contract (docs/analysis.md):

    x = float(traced)  # mxlint: disable=trace-host-sync -- verdict scalar,
                       # one round-trip per step by design

``disable=`` names one or more comma-separated rule ids; the text after
``--`` is a REQUIRED justification.  A disable comment without a
justification does not suppress anything and itself raises
``bad-suppression`` — an unexplained suppression is how invariants rot.
The comment suppresses findings on its own line, or (as a standalone
comment line) on the next code line.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
import subprocess
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warning", "info")

#: bump when ANY rule's logic changes: it keys the incremental cache,
#: and a stale record must never survive an analyzer upgrade
ENGINE_VERSION = "3.2"

# id of the meta-rule emitted for malformed disable comments; it cannot
# itself be suppressed (suppressing the suppression-checker is turtles).
BAD_SUPPRESSION = "bad-suppression"

# project-scope roots: cross-file facts (docs symbol index, registry
# table, lock graph) are always gathered over these subtrees of the
# root when they exist, regardless of which subset a run analyzes —
# linting one file must not make every doc row look stale
PROJECT_SCOPE = ("mxnet_tpu", "tools", "bench.py")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str = "error"
    suppressed: bool = False
    justification: Optional[str] = None

    def to_dict(self):
        return dataclasses.asdict(self)

    def render(self):
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.severity}] {self.rule}: {self.message}{tag}")


@dataclasses.dataclass
class ModuleInfo:
    """One parsed source file, shared by every rule."""
    path: Path          # absolute
    relpath: str        # repo-root-relative (stable in output/tests)
    source: str
    tree: ast.Module
    lines: List[str]


class Rule:
    """Per-file rule: ``check_module`` yields findings for one file."""

    id: str = ""
    default_severity: str = "error"
    description: str = ""

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finding(self, mod, node, message, rule_id=None):
        return Finding(rule=rule_id or self.id, path=mod.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message)


class ProjectRule(Rule):
    """Whole-project rule: extracts a JSON-serializable facts record per
    file (cached alongside findings) and judges the union.

    ``check_facts(facts, root, analyzed)`` receives ``facts`` as a list
    of ``(relpath, record)`` pairs covering the analyzed set plus the
    project scope, and ``analyzed`` as the set of relpaths this run was
    actually asked about — findings anchored in source files should be
    restricted to it (docs findings are the exception: they anchor in
    the doc, which is never "analyzed")."""

    def facts(self, mod: ModuleInfo):
        return None

    def check_facts(self, facts: List[Tuple[str, object]], root: Path,
                    analyzed: set) -> Iterable[Finding]:
        return ()


# --------------------------------------------------------------------------
# suppression comments
# --------------------------------------------------------------------------

_DISABLE_RE = re.compile(
    r"#\s*mxlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s*--\s*(.*\S))?\s*$")


def parse_suppressions(mod: ModuleInfo):
    """line -> (set of rule ids, justification | None) and the
    bad-suppression findings for comments missing a justification.

    A suppression comment applies to its own line; when the line holds
    ONLY the comment, it applies to the next line instead (the long-line
    form).  Consecutive standalone comment lines chain, so a wrapped
    justification still points at the first code line after the block.
    """
    table: Dict[int, Tuple[set, Optional[str]]] = {}
    bad: List[Finding] = []
    pending: Optional[Tuple[set, Optional[str]]] = None
    for i, text in enumerate(mod.lines, start=1):
        m = _DISABLE_RE.search(text)
        stripped = text.strip()
        if m:
            rules = {r.strip() for r in m.group(1).split(",")}
            just = m.group(2)
            if not just:
                bad.append(Finding(
                    rule=BAD_SUPPRESSION, path=mod.relpath, line=i, col=1,
                    message=f"mxlint disable={','.join(sorted(rules))} has "
                            f"no justification: write "
                            f"'# mxlint: disable=RULE -- why it is safe'"))
                pending = None
                continue
            if stripped.startswith("#"):
                pending = (rules, just)      # standalone: arm for next code line
            else:
                table[i] = (rules, just)     # inline
                pending = None
        elif pending is not None:
            if stripped.startswith("#") or not stripped:
                continue                     # comment block / blank: keep arming
            table[i] = pending
            pending = None
    return table, bad


# --------------------------------------------------------------------------
# config + engine
# --------------------------------------------------------------------------

class Config:
    """Per-rule enable/severity knobs (CLI: --disable / --severity)."""

    def __init__(self, disabled=(), severities=None):
        self.disabled = set(disabled)
        self.severities = dict(severities or {})
        for rid, sev in self.severities.items():
            if sev not in SEVERITIES:
                raise ValueError(f"unknown severity {sev!r} for rule {rid!r} "
                                 f"(one of {SEVERITIES})")

    def enabled(self, rule_id):
        return rule_id not in self.disabled

    def severity(self, rule: Rule):
        return self.severities.get(rule.id, rule.default_severity)

    def severity_of(self, rule_id, default="error"):
        return self.severities.get(rule_id, default)


def default_rules() -> List[Rule]:
    from .trace_rules import (HostSyncRule, TracedBranchRule,
                              MutableGlobalRule, UnhashableStaticRule)
    from .thread_rules import UnlockedAttrRule
    from .donation_rules import DonatedReuseRule
    from .compile_rules import JitInLoopRule, UnbudgetedEntrypointRule
    from .concurrency_rules import (BlockingUnderLockRule, LockOrderRule,
                                    SignalHandlerRule)
    from .lifecycle_rules import ResourceLeakRule
    from .registry_rules import (DuplicateRegistrationRule,
                                 MissingGradientRule, StaleDocSymbolRule)
    from .spmd_rules import (SpmdAxisUnknownRule, SpmdSpecArityRule,
                             SpmdReplicationClaimRule,
                             SpmdCollectiveInLoopRule)

    return [HostSyncRule(), TracedBranchRule(), MutableGlobalRule(),
            UnhashableStaticRule(), UnlockedAttrRule(), DonatedReuseRule(),
            BlockingUnderLockRule(), LockOrderRule(), SignalHandlerRule(),
            ResourceLeakRule(), JitInLoopRule(),
            SpmdAxisUnknownRule(), SpmdSpecArityRule(),
            SpmdReplicationClaimRule(), SpmdCollectiveInLoopRule(),
            DuplicateRegistrationRule(), MissingGradientRule(),
            StaleDocSymbolRule(), UnbudgetedEntrypointRule()]


def _collect_files(paths) -> List[Path]:
    out = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(f for f in p.rglob("*.py")
                              if ".mxlint_cache" not in f.parts))
        elif p.suffix == ".py":
            out.append(p)
    return out


def _relpath(path: Path, root: Path) -> str:
    try:
        return str(path.resolve().relative_to(root.resolve()))
    except ValueError:
        return str(path)


def load_module(path: Path, root: Path,
                source: Optional[str] = None) -> Optional[ModuleInfo]:
    if source is None:
        source = path.read_text(encoding="utf-8", errors="replace")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError:
        return None  # a syntax error is the interpreter's finding, not ours
    return ModuleInfo(path=path, relpath=_relpath(path, root),
                      source=source, tree=tree, lines=source.splitlines())


def _git_changed(root: Path) -> Optional[set]:
    """RESOLVED absolute paths differing from HEAD (tracked changes +
    untracked files), or None when git is unavailable — the caller then
    falls back to analyzing everything (fail open, never silently
    narrow).  git reports paths relative to the repository TOPLEVEL,
    which need not be ``root`` (linting a subpackage), so names are
    anchored there before comparison."""
    try:
        top = subprocess.run(
            ["git", "-C", str(root), "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=15)
        if top.returncode != 0 or not top.stdout.strip():
            return None
        toplevel = Path(top.stdout.strip())
        # run from the toplevel: `diff --name-only` is toplevel-relative
        # but `ls-files` is cwd-relative — one anchor for both
        diff = subprocess.run(
            ["git", "-C", str(toplevel), "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, timeout=15)
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "-C", str(toplevel), "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=15)
        names = {l.strip() for l in diff.stdout.splitlines() if l.strip()}
        if untracked.returncode == 0:
            names |= {l.strip() for l in untracked.stdout.splitlines()
                      if l.strip()}
        return {(toplevel / n).resolve() for n in names}
    except Exception:
        return None


def _cache_signature(rules) -> str:
    pyver = ".".join(str(v) for v in sys.version_info[:2])
    return f"mxlint-{ENGINE_VERSION}-py{pyver}-" \
           + ",".join(sorted(r.id for r in rules))


def _file_record(path: Path, root: Path, per_file, project, cache,
                 findings_needed: bool = True):
    """Per-file analysis record: raw findings of every per-file rule,
    the suppression table, bad-suppression findings, and each project
    rule's facts.  Pure function of the file content (plus the rule
    set), which is exactly the cache key.

    ``findings_needed=False`` is the facts-only path for PROJECT_SCOPE
    extras: the (expensive) per-file rule suite is skipped and the
    record is marked ``partial`` — a later run that needs the same
    file's findings treats a partial record as a cache miss and
    upgrades it."""
    relpath = _relpath(path, root)
    try:
        data = path.read_bytes()
    except OSError:
        return {"relpath": relpath, "findings": [], "bad": [],
                "suppress": {}, "facts": {}}
    key = cache.key(relpath, data) if cache is not None else None
    if key is not None:
        rec = cache.get(relpath, key)
        if rec is not None and rec.get("relpath") == relpath \
                and not (findings_needed and rec.get("partial")):
            return rec
    mod = load_module(path, root,
                      source=data.decode("utf-8", errors="replace"))
    if mod is None:
        rec = {"relpath": relpath, "findings": [], "bad": [],
               "suppress": {}, "facts": {}}
    else:
        table, bad = parse_suppressions(mod)
        findings = []
        if findings_needed:
            for rule in per_file:
                for f in rule.check_module(mod):
                    findings.append({"rule": f.rule, "line": f.line,
                                     "col": f.col, "message": f.message})
        rec = {
            "relpath": relpath,
            "findings": findings,
            "bad": [{"line": b.line, "col": b.col, "message": b.message}
                    for b in bad],
            "suppress": {str(line): [sorted(rules), just]
                         for line, (rules, just) in table.items()},
            "facts": {},
        }
        if not findings_needed:
            rec["partial"] = True
        for rule in project:
            fact = rule.facts(mod)
            if fact is not None:
                rec["facts"][rule.id] = fact
    if key is not None:
        cache.put(relpath, key, rec)
    return rec


def analyze(paths, config: Optional[Config] = None, rules=None,
            root: Optional[Path] = None, use_cache: bool = False,
            cache_dir=None, changed_only: bool = False) -> List[Finding]:
    """Run every enabled rule over ``paths`` (files or directories).

    Returns ALL findings, with suppressed ones marked rather than
    dropped — the JSON output keeps them visible (an audit of what is
    being waived), the exit code ignores them.

    ``use_cache=True`` reads/writes per-file records under
    ``<root>/.mxlint_cache/`` (or ``cache_dir``); only files whose
    content changed are re-analyzed.  ``changed_only=True`` restricts
    the analyzed set to files ``git`` reports as differing from HEAD
    (the ``--changed`` CLI flag).  Passing a custom ``rules`` list
    disables the cache — cached records are keyed on the default rule
    set's identity, not arbitrary rule objects.
    """
    config = config or Config()
    custom_rules = rules is not None
    rules = list(rules) if custom_rules else default_rules()
    root = Path(root) if root is not None else Path.cwd()
    per_file = [r for r in rules if not isinstance(r, ProjectRule)]
    project = [r for r in rules if isinstance(r, ProjectRule)]
    defaults = {r.id: r.default_severity for r in rules}

    files = _collect_files(paths)
    if changed_only:
        changed = _git_changed(root)
        if changed is not None:
            files = [f for f in files if f.resolve() in changed]

    cache = None
    if use_cache and not custom_rules:
        from .cache import FileCache
        cache = FileCache(root, cache_dir,
                          signature=_cache_signature(rules))

    records = []
    analyzed_rel = set()
    seen_paths = set()
    for f in files:
        rp = f.resolve()
        if rp in seen_paths:
            continue
        seen_paths.add(rp)
        rec = _file_record(f, root, per_file, project, cache)
        rec["_analyzed"] = True
        analyzed_rel.add(rec["relpath"])
        records.append(rec)
    if project:
        extra = []
        for sub in PROJECT_SCOPE:
            p = root / sub
            if p.exists():
                extra.extend(_collect_files([p]))
        for f in extra:
            rp = f.resolve()
            if rp in seen_paths:
                continue
            seen_paths.add(rp)
            rec = _file_record(f, root, per_file, project, cache,
                               findings_needed=False)
            rec["_analyzed"] = False
            records.append(rec)

    findings: List[Finding] = []
    for rec in records:
        if not rec["_analyzed"]:
            continue
        for fd in rec["findings"]:
            rid = fd["rule"]
            if not config.enabled(rid):
                continue
            findings.append(Finding(
                rule=rid, path=rec["relpath"], line=fd["line"],
                col=fd["col"], message=fd["message"],
                severity=config.severity_of(rid,
                                            defaults.get(rid, "error"))))
        if config.enabled(BAD_SUPPRESSION):
            for bd in rec["bad"]:
                findings.append(Finding(
                    rule=BAD_SUPPRESSION, path=rec["relpath"],
                    line=bd["line"], col=bd["col"],
                    message=bd["message"]))

    for rule in project:
        if not config.enabled(rule.id):
            continue
        fact_list = [(rec["relpath"], rec["facts"][rule.id])
                     for rec in records if rule.id in rec["facts"]]
        sev = config.severity(rule)
        for f in rule.check_facts(fact_list, root, analyzed_rel):
            f.severity = sev
            findings.append(f)

    # apply suppressions (bad-suppression is exempt by design)
    tables = {rec["relpath"]: rec["suppress"] for rec in records
              if rec["_analyzed"]}
    for f in findings:
        if f.rule == BAD_SUPPRESSION:
            continue
        hit = tables.get(f.path, {}).get(str(f.line))
        if hit and f.rule in set(hit[0]):
            f.suppressed = True
            f.justification = hit[1]

    # sort + dedupe: interprocedural walks legitimately reach the same
    # site via several paths (helper under two locks, finally bodies
    # duplicated per continuation) — one finding per anchor
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    out, seen = [], set()
    for f in findings:
        k = (f.rule, f.path, f.line, f.col)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out


def summarize(findings: List[Finding]) -> str:
    active = [f for f in findings if not f.suppressed]
    sup = len(findings) - len(active)
    errs = sum(1 for f in active if f.severity == "error")
    return (f"{len(active)} finding(s) ({errs} error(s)), "
            f"{sup} suppressed")


def to_json(findings: List[Finding]) -> str:
    return json.dumps([f.to_dict() for f in findings], indent=2)


def exit_code(findings: List[Finding]) -> int:
    return 1 if any(not f.suppressed and f.severity == "error"
                    for f in findings) else 0


# --------------------------------------------------------------------------
# shared AST helpers (used by the rule modules)
# --------------------------------------------------------------------------

def dotted_name(node) -> Optional[str]:
    """'jax.numpy.asarray' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_component(node) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def assigned_names(target) -> set:
    """Names bound by an assignment target (handles tuple unpacking)."""
    out = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,
                                                          ast.Del)):
            out.add(n.id)
    return out
