"""mxlint rule engine.

The analysis counterpart of the runtime's fault harness: where
``mx.fault`` makes concurrency/preemption failures *repeatable*, mxlint
makes the invariants that PREVENT them *mechanical*.  TensorFlow's
production experience (PAPERS.md: Abadi et al.) is that large dataflow
frameworks survive on invariant checking in CI, not review; the
whole-program-compile stacks (Julia→TPU, PAPERS.md) show that
trace/compile-boundary discipline is the correctness frontier.  This
engine walks Python sources with ``ast`` (no imports, no execution — it
must be runnable on a broken tree) and applies per-file and
whole-project rules.

Suppression contract (docs/analysis.md):

    x = float(traced)  # mxlint: disable=trace-host-sync -- verdict scalar,
                       # one round-trip per step by design

``disable=`` names one or more comma-separated rule ids; the text after
``--`` is a REQUIRED justification.  A disable comment without a
justification does not suppress anything and itself raises
``bad-suppression`` — an unexplained suppression is how invariants rot.
The comment suppresses findings on its own line, or (as a standalone
comment line) on the next code line.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

SEVERITIES = ("error", "warning", "info")

# id of the meta-rule emitted for malformed disable comments; it cannot
# itself be suppressed (suppressing the suppression-checker is turtles).
BAD_SUPPRESSION = "bad-suppression"


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str = "error"
    suppressed: bool = False
    justification: Optional[str] = None

    def to_dict(self):
        return dataclasses.asdict(self)

    def render(self):
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.severity}] {self.rule}: {self.message}{tag}")


@dataclasses.dataclass
class ModuleInfo:
    """One parsed source file, shared by every rule."""
    path: Path          # absolute
    relpath: str        # repo-root-relative (stable in output/tests)
    source: str
    tree: ast.Module
    lines: List[str]


class Rule:
    """Per-file rule: ``check_module`` yields findings for one file."""

    id: str = ""
    default_severity: str = "error"
    description: str = ""

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def finding(self, mod: ModuleInfo, node, message, rule_id=None):
        return Finding(rule=rule_id or self.id, path=mod.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message)


class ProjectRule(Rule):
    """Whole-project rule: sees every module at once (cross-file state
    like the op registry, plus non-Python inputs like docs/api.md)."""

    def check_project(self, modules: List[ModuleInfo],
                      root: Path) -> Iterable[Finding]:
        return ()


# --------------------------------------------------------------------------
# suppression comments
# --------------------------------------------------------------------------

_DISABLE_RE = re.compile(
    r"#\s*mxlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
    r"(?:\s*--\s*(.*\S))?\s*$")


def parse_suppressions(mod: ModuleInfo):
    """line -> (set of rule ids, justification | None) and the
    bad-suppression findings for comments missing a justification.

    A suppression comment applies to its own line; when the line holds
    ONLY the comment, it applies to the next line instead (the long-line
    form).  Consecutive standalone comment lines chain, so a wrapped
    justification still points at the first code line after the block.
    """
    table: Dict[int, Tuple[set, Optional[str]]] = {}
    bad: List[Finding] = []
    pending: Optional[Tuple[set, Optional[str]]] = None
    for i, text in enumerate(mod.lines, start=1):
        m = _DISABLE_RE.search(text)
        stripped = text.strip()
        if m:
            rules = {r.strip() for r in m.group(1).split(",")}
            just = m.group(2)
            if not just:
                bad.append(Finding(
                    rule=BAD_SUPPRESSION, path=mod.relpath, line=i, col=1,
                    message=f"mxlint disable={','.join(sorted(rules))} has "
                            f"no justification: write "
                            f"'# mxlint: disable=RULE -- why it is safe'"))
                pending = None
                continue
            if stripped.startswith("#"):
                pending = (rules, just)      # standalone: arm for next code line
            else:
                table[i] = (rules, just)     # inline
                pending = None
        elif pending is not None:
            if stripped.startswith("#") or not stripped:
                continue                     # comment block / blank: keep arming
            table[i] = pending
            pending = None
    return table, bad


# --------------------------------------------------------------------------
# config + engine
# --------------------------------------------------------------------------

class Config:
    """Per-rule enable/severity knobs (CLI: --disable / --severity)."""

    def __init__(self, disabled=(), severities=None):
        self.disabled = set(disabled)
        self.severities = dict(severities or {})
        for rid, sev in self.severities.items():
            if sev not in SEVERITIES:
                raise ValueError(f"unknown severity {sev!r} for rule {rid!r} "
                                 f"(one of {SEVERITIES})")

    def enabled(self, rule_id):
        return rule_id not in self.disabled

    def severity(self, rule: Rule):
        return self.severities.get(rule.id, rule.default_severity)


def default_rules() -> List[Rule]:
    from .trace_rules import (HostSyncRule, TracedBranchRule,
                              MutableGlobalRule, UnhashableStaticRule)
    from .thread_rules import UnlockedAttrRule
    from .donation_rules import DonatedReuseRule
    from .registry_rules import (DuplicateRegistrationRule,
                                 MissingGradientRule, StaleDocSymbolRule)

    return [HostSyncRule(), TracedBranchRule(), MutableGlobalRule(),
            UnhashableStaticRule(), UnlockedAttrRule(), DonatedReuseRule(),
            DuplicateRegistrationRule(), MissingGradientRule(),
            StaleDocSymbolRule()]


def _collect_files(paths) -> List[Path]:
    out = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    return out


def load_module(path: Path, root: Path) -> Optional[ModuleInfo]:
    source = path.read_text(encoding="utf-8", errors="replace")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError:
        return None  # a syntax error is the interpreter's finding, not ours
    try:
        rel = str(path.resolve().relative_to(root.resolve()))
    except ValueError:
        rel = str(path)
    return ModuleInfo(path=path, relpath=rel, source=source, tree=tree,
                      lines=source.splitlines())


def analyze(paths, config: Optional[Config] = None, rules=None,
            root: Optional[Path] = None) -> List[Finding]:
    """Run every enabled rule over ``paths`` (files or directories).

    Returns ALL findings, with suppressed ones marked rather than
    dropped — the JSON output keeps them visible (an audit of what is
    being waived), the exit code ignores them.
    """
    config = config or Config()
    rules = list(rules) if rules is not None else default_rules()
    root = Path(root) if root is not None else Path.cwd()
    files = _collect_files(paths)
    modules = [m for m in (load_module(f, root) for f in files)
               if m is not None]

    findings: List[Finding] = []
    suppress_tables = {}
    for mod in modules:
        table, bad = parse_suppressions(mod)
        suppress_tables[mod.relpath] = table
        if config.enabled(BAD_SUPPRESSION):
            findings.extend(bad)
    for rule in rules:
        if not config.enabled(rule.id):
            continue
        sev = config.severity(rule)
        emitted: Iterable[Finding]
        if isinstance(rule, ProjectRule):
            emitted = rule.check_project(modules, root)
        else:
            emitted = (f for mod in modules for f in rule.check_module(mod))
        for f in emitted:
            f.severity = sev
            findings.append(f)

    # apply suppressions (bad-suppression is exempt by design)
    for f in findings:
        if f.rule == BAD_SUPPRESSION:
            continue
        table = suppress_tables.get(f.path, {})
        hit = table.get(f.line)
        if hit and f.rule in hit[0]:
            f.suppressed = True
            f.justification = hit[1]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def summarize(findings: List[Finding]) -> str:
    active = [f for f in findings if not f.suppressed]
    sup = len(findings) - len(active)
    errs = sum(1 for f in active if f.severity == "error")
    return (f"{len(active)} finding(s) ({errs} error(s)), "
            f"{sup} suppressed")


def to_json(findings: List[Finding]) -> str:
    return json.dumps([f.to_dict() for f in findings], indent=2)


def exit_code(findings: List[Finding]) -> int:
    return 1 if any(not f.suppressed and f.severity == "error"
                    for f in findings) else 0


# --------------------------------------------------------------------------
# shared AST helpers (used by the rule modules)
# --------------------------------------------------------------------------

def dotted_name(node) -> Optional[str]:
    """'jax.numpy.asarray' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_component(node) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def assigned_names(target) -> set:
    """Names bound by an assignment target (handles tuple unpacking)."""
    out = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store,
                                                          ast.Del)):
            out.add(n.id)
    return out
