#!/usr/bin/env python
"""Tunnel watcher: fire the on-chip queue the moment the axon backend answers.

The axon TPU tunnel wedges for hours at a time (it hung for the entirety of
build rounds 3 and 4).  Hand-probing wastes build time and loses the window
when the tunnel briefly breathes, so this watcher automates PERF.md's on-chip
queue (VERDICT r4 task #1):

  * every PROBE_INTERVAL seconds, probe `jax.devices()` in a subprocess with a
    hard timeout (the wedge mode is an indefinite hang, not an error);
  * when the probe answers with a real TPU, run the queue steps in order, each
    in its own subprocess with its own timeout so a mid-run re-wedge only
    loses that step;
  * after each successful step, `git commit` its artifact immediately (scoped
    `git commit -- <paths>` so a concurrently working build session's staged
    files are not swept in);
  * steps that fail or time out stay queued and retry on the next alive probe.

State lives in TPU_WATCH_STATE.json at the repo root; log in tools/tpu_watch.log.
Run:  nohup python tools/tpu_watch.py &   (or via the build session's
background shell).  Exits when every step has succeeded.

The dataloader --threads sweep from VERDICT task #5 is NOT in this queue: the
tunnel only proxies device execution — host-side decode still runs on this
1-vCPU dev machine, so a multi-thread sweep here measures nothing.  It needs
a real multi-core TPU-VM host; see PERF.md "on-chip queue" notes.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATE_PATH = os.path.join(REPO, "TPU_WATCH_STATE.json")
LOG_PATH = os.path.join(REPO, "tools", "tpu_watch.log")

PROBE_INTERVAL = 600       # seconds between probes while wedged
PROBE_TIMEOUT = 120        # a healthy tunnel answers in ~5-20 s

# (name, argv, artifact paths, timeout_s, extra_env).  Ordered cheapest-first
# so a brief tunnel window still yields the highest-value evidence: the
# compile-only fused-conv smoke distinguishes "Mosaic rejects the kernel"
# from "numerics drift" (VERDICT r4 weak #2) before the expensive full suite.
QUEUE = [
    ("fused_conv_compile_smoke",
     [sys.executable, "-m", "pytest", "tests_tpu/test_fused_conv_tpu.py",
      "-q", "-k", "compile_only", "--no-header"],
     ["TPU_FUSED_COMPILE_r05.md"], 1800, {}),
    ("bench_default",
     [sys.executable, "bench.py"],
     ["BENCH_builder_r05.json"], 2400, {}),
    ("bench_fused_ab",
     # The fused-ResNet train step instantiates ~150 Mosaic kernel programs
     # inside ONE jit computation; the whole-program compile must finish
     # once within the inner watchdog before the persistent cache can help.
     # Outer >= 2x inner + probe/backoff so bench.py's own retry and its
     # parseable error line can actually run before the step is killed.
     [sys.executable, "bench.py"],
     ["BENCH_builder_r05_fused.json"], 6000,
     {"MXTPU_BENCH_FUSED": "1", "MXTPU_BENCH_TIMEOUT": "2700"}),
    ("hlo_costs_default",
     [sys.executable, "benchmark/hlo_costs.py"],
     ["HLO_COSTS_r05.md"], 2400, {}),
    ("hlo_costs_fused",
     [sys.executable, "benchmark/hlo_costs.py"],
     ["HLO_COSTS_r05_fused.md"], 2400, {"MXTPU_BENCH_FUSED": "1"}),
    ("bench_ssd",
     # SSD-512's first train-step compile blew bench.py's default 1500s inner
     # watchdog in the round-5 bench_all run; give the dedicated step a
     # 2700s inner budget, outer sized for bench.py's probe + single retry.
     [sys.executable, "bench.py", "ssd"],
     ["BENCH_builder_r05_ssd.json"], 6000, {"MXTPU_BENCH_TIMEOUT": "2700"}),
    ("bench_batch512",
     # batch-size A/B: larger per-chip batch amortises dispatch + norm
     # overheads; per-image rate printed, so directly comparable to the
     # batch-256 default
     [sys.executable, "bench.py"],
     ["BENCH_builder_r05_b512.json"], 5400,
     {"MXTPU_BENCH_BATCH": "512", "MXTPU_BENCH_TIMEOUT": "2400"}),
    ("bench_all",
     [sys.executable, "bench.py", "all"],
     ["BENCH_builder_r05_all.json"], 4800, {}),
    ("tests_tpu",
     [sys.executable, "-m", "pytest", "tests_tpu/", "-q"],
     ["TPU_TESTS_r05.md"], 10800, {}),
]


def log(msg):
    line = "[%s] %s" % (time.strftime("%Y-%m-%d %H:%M:%S"), msg)
    print(line, flush=True)
    with open(LOG_PATH, "a") as f:
        f.write(line + "\n")


MAX_ATTEMPTS = 3           # per-step cap so one red step can't starve the rest


def load_state():
    try:
        with open(STATE_PATH) as f:
            st = json.load(f)
            st.setdefault("attempts", {})
            return st
    except (OSError, ValueError):
        return {"done": [], "probes": 0, "alive_at": None, "attempts": {}}


def save_state(state):
    with open(STATE_PATH, "w") as f:
        json.dump(state, f, indent=1)


def probe():
    """True iff jax sees a non-CPU device within PROBE_TIMEOUT."""
    code = ("import jax; ds = jax.devices(); "
            "import sys; sys.exit(0 if ds and ds[0].platform != 'cpu' else 3)")
    try:
        r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                           timeout=PROBE_TIMEOUT, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_step(name, argv, artifacts, timeout_s, extra_env=None):
    env = dict(os.environ)
    env.update(extra_env or {})
    log("step %s: starting (timeout %ds)" % (name, timeout_s))
    t0 = time.time()
    try:
        r = subprocess.run(argv, cwd=REPO, timeout=timeout_s,
                           capture_output=True, text=True, env=env)
    except subprocess.TimeoutExpired as e:
        log("step %s: TIMED OUT after %ds (tunnel likely re-wedged)"
            % (name, timeout_s))
        partial = (e.stdout or b"")
        if isinstance(partial, bytes):
            partial = partial.decode("utf-8", "replace")
        with open(os.path.join(REPO, artifacts[0]), "w") as f:
            f.write("# step %s TIMED OUT after %ds at %s\n%s" %
                    (name, timeout_s, time.strftime("%F %T"), partial[-20000:]))
        # a timed-out log is still on-chip evidence: commit it like the rest
        subprocess.run(["git", "add", "--"] + artifacts, cwd=REPO)
        subprocess.run(["git", "commit", "-q", "-m",
                        "on-chip artifact: %s (timeout, tpu_watch)" % name,
                        "--"] + artifacts, cwd=REPO)
        return False
    dt = time.time() - t0
    body = ("# on-chip artifact: %s  (builder-measured via tpu_watch, "
            "round 5, %s, rc=%d, %.0fs)\n\n```\n%s\n```\n\nstderr tail:\n"
            "```\n%s\n```\n" % (name, time.strftime("%F %T"), r.returncode,
                                dt, r.stdout[-40000:], r.stderr[-8000:]))
    with open(os.path.join(REPO, artifacts[0]), "w") as f:
        f.write(body)
    ok = r.returncode == 0
    log("step %s: rc=%d in %.0fs -> %s" % (name, r.returncode, dt,
                                           artifacts[0]))
    # commit the artifact either way — a red on-chip log is still evidence
    subprocess.run(["git", "add", "--"] + artifacts, cwd=REPO)
    subprocess.run(["git", "commit", "-q",
                    "-m", "on-chip artifact: %s (%s, tpu_watch)" %
                    (name, "green" if ok else "rc=%d" % r.returncode),
                    "--"] + artifacts, cwd=REPO)
    return ok


def main():
    state = load_state()
    log("watcher up; done=%s" % state["done"])
    while True:
        pending = [s for s in QUEUE if s[0] not in state["done"]]
        if not pending:
            log("queue drained — all on-chip steps green; exiting")
            return 0
        state["probes"] += 1
        alive = probe()
        if not alive:
            if state["probes"] % 6 == 1:
                log("probe #%d: tunnel wedged (pending: %s)"
                    % (state["probes"], [s[0] for s in pending]))
            save_state(state)
            time.sleep(PROBE_INTERVAL)
            continue
        state["alive_at"] = time.strftime("%F %T")
        log("probe #%d: TUNNEL ALIVE — firing queue (%d pending)"
            % (state["probes"], len(pending)))
        save_state(state)
        for name, argv, artifacts, timeout_s, extra_env in pending:
            if state["attempts"].get(name, 0) >= MAX_ATTEMPTS:
                continue  # persistently red: its artifact is committed; move on
            state["attempts"][name] = state["attempts"].get(name, 0) + 1
            if run_step(name, argv, artifacts, timeout_s, extra_env):
                state["done"].append(name)
                save_state(state)
            else:
                # Failed: distinguish "step is red" (tunnel alive — keep
                # draining the rest of the queue; round-4 bug: a red first
                # step starved every later step) from "tunnel re-wedged
                # mid-step" (refund the attempt — the step never saw a
                # healthy tunnel — and back off until the next alive probe).
                if not probe():
                    state["attempts"][name] -= 1
                    save_state(state)
                    log("tunnel re-wedged mid-queue; backing off")
                    break
                save_state(state)
        still_pending = [s for s in QUEUE if s[0] not in state["done"]]
        if still_pending and all(state["attempts"].get(s[0], 0) >= MAX_ATTEMPTS
                                 for s in still_pending):
            log("every pending step exhausted %d attempts; exiting "
                "(red artifacts are committed)" % MAX_ATTEMPTS)
            return 1
        time.sleep(60)


if __name__ == "__main__":
    sys.exit(main())
