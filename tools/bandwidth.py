"""KVStore bandwidth microbenchmark.

ref: tools/bandwidth/measure.py — measures push/pull throughput of a
kvstore across devices for a range of array sizes; used to size
gradient-aggregation traffic.  TPU-native: the same sweep over the
collective-backed kvstore (ICI on real hardware; on CPU it exercises the
virtual mesh).

    python tools/bandwidth.py [--kvstore device] [--sizes 1e5,1e6,1e7]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import engine  # noqa: E402


def measure(kv_type="device", sizes=(100_000, 1_000_000, 10_000_000),
            repeat=10, emit_json=False):
    kv = mx.kv.create(kv_type)
    results = []
    for n in sizes:
        n = int(n)
        key = f"bw_{n}"
        grad = mx.nd.array(np.random.RandomState(0).randn(n)
                           .astype(np.float32))
        kv.init(key, mx.nd.zeros((n,)))
        out = mx.nd.zeros((n,))
        kv.push(key, grad)          # warm the compiled path
        kv.pull(key, out=out)
        out.wait_to_read()
        t0 = time.perf_counter()
        for _ in range(repeat):
            # each push chains on the previous pull so no iteration can be
            # served from a cached/idempotent result
            kv.push(key, out + 1.0)
            kv.pull(key, out=out)
        out.wait_to_read()
        engine.waitall()
        dt = (time.perf_counter() - t0) / repeat
        nbytes = n * 4
        gbps = 2 * nbytes / dt / 1e9  # push + pull
        results.append({"size": n, "bytes": nbytes,
                        "avg_roundtrip_ms": round(dt * 1e3, 3),
                        "GB_per_s": round(gbps, 3)})
    for r in results:
        if emit_json:
            print(json.dumps(r))
        else:
            print(f"size {r['size']:>12,}  {r['avg_roundtrip_ms']:>10.3f} ms"
                  f"  {r['GB_per_s']:>8.3f} GB/s")
    return results


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--kvstore", default="device")
    ap.add_argument("--sizes", default="1e5,1e6,1e7")
    ap.add_argument("--repeat", type=int, default=10)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    sizes = [int(float(s)) for s in args.sizes.split(",")]
    measure(args.kvstore, sizes, args.repeat, args.json)


if __name__ == "__main__":
    main()
