#!/usr/bin/env python
"""Chaos smoke: kill-and-resume (train), inject-and-drain (serve),
replica-kill + rolling-update (fleet), the incremental-analyzer
contract (lint), and the budget-audit contract (cost).

``--mode train`` (default) runs a small training loop with periodic
checkpoints, injects a crash mid-run via ``fault.inject``, rediscovers
the newest snapshot with ``resume_latest``, and checks the resumed loss
trajectory matches an uninterrupted run bit-exactly — the acceptance
contract of ISSUE 2.

``--mode serve`` starts an ``mx.serving.InferenceServer``, drives it
from client threads while injecting a ``serving.step`` failure burst,
then lands a SIGTERM mid-flight: the drain must complete with every
ACCEPTED request resolved (result or explicit error — zero silently
dropped) and the breaker must have tripped and fast-failed — the
acceptance contract of ISSUE 4::

    python tools/chaos_check.py [--mode train|serve|lint] [--steps 8] ...

``--mode fleet`` runs the ISSUE 7 acceptance end to end: a 3-replica
``mx.serving.ServingFleet`` under continuous client traffic has one
replica hard-killed mid-flight, two training snapshots (written by a
real ``TrainStep`` + ``CheckpointManager``) streamed through a rolling
weight update, and finally a SIGTERM drain.  The contract: **zero
dropped accepted requests** end to end (every fleet-accepted request
resolves with a result) and **zero recompiles** (the runtime jit-cache
count equals the static bucket census before and after both swaps).
A second leg (ISSUE 8) then boots an **int8 fleet** (per-channel PTQ
weights via ``amp.Int8Quantizer``, dequant folded into the compiled
apply) and streams a fresh **f32** training snapshot through a rolling
update under traffic — re-quantized on ingest by the fleet's
quantizer, 0 drops, census unchanged.

``--mode llm`` runs the ISSUE 10 acceptance end to end — against a
**tensor-parallel sharded gang** since ISSUE 14: a
``mx.serving.GenerationServer(tp_shards=2, tp_collectives="int8")``
(head-sharded paged KV pools, Megatron-sharded weights, quantized
decode collectives, one pinned multi-device decode executable) streams
generations from client threads while a ``generate.decode`` failure
burst fires, then lands a SIGTERM mid-decode.  The contract: **zero
dropped accepted sequences** (every accepted ``Request`` resolves to
tokens or an explicit error), **zero recompiles** (runtime jit-cache
count == the prefill-grid + 1 census before and after the chaos —
sharding must not add an executable), and **pages fully reclaimed**
after the drain (free list == allocatable pool size).

``--mode lint`` runs the full mxlint analyzer twice against a fresh
cache directory and asserts the second (fully cached) run is >= 5x
faster AND byte-identical in findings — the incremental-mode contract
of ISSUE 5 (a cache that changes answers is worse than no cache).

``--mode cost`` runs the full costguard budget audit (every committed
golden in tests/goldens/budgets/) twice against a fresh report cache:
the cold run compiles every entry point, the warm run must hit the
HLO-hash report cache (lowering still runs — that is what keys the
cache), come back byte-identical in verdicts, pass the budget check
both times, and land inside the wall-clock budgets — the ISSUE 6
analogue of the lint contract.

``--mode hlo`` runs the full hloguard structural audit (every surface
with a golden in tests/goldens/hloguard/) twice against a fresh facts
cache, with every lowering prebuilt OUTSIDE the timed window: the cold
run parses/extracts facts from ~2 MB of StableHLO text, the warm run
must hit the HLO-hash facts cache, come back byte-identical in
verdicts (findings, suppressions and censuses included), be >= 5x
faster, and pass the structural gate both times — the ISSUE 18
analogue of the lint and cost contracts.

``--mode elastic`` runs the ISSUE 9 acceptance end to end: an
``elastic.Supervisor`` drives a real 2-worker CPU training gang
(``tests/elastic_worker.py``) to a target step while the harness
SIGKILLs one worker mid-epoch, SIGSTOPs the other to force a watchdog
trip, and finally (fresh gang) SIGTERMs the supervisor itself.  The
contract: the job reaches the target step, restarts stay within the
progress-aware budget, every restarted attempt resumes from a strictly
increasing committed step (never step 0), the supervisor SIGTERM ends
with every worker exiting ``EXIT_PREEMPTED`` after its snapshot, the
event log parses as JSONL, and zero worker processes leak.

``--mode slo`` runs the ISSUE 12 acceptance end to end: a mixed-tenant
traffic storm (two priority classes with per-tenant token buckets, one
abusive tenant) against a grouped ``ServingFleet`` while — all at once —
one replica is hard-killed, a ``FleetAutoscaler`` runs a full scale-up/
scale-down cycle, and a rolling weight update streams through; then a
disaggregated ``GenerationServer`` (prefill worker group + handoff)
serves a long-prefill + decode mix under the same two classes.  The
contract: **0 dropped accepted requests** on both legs, **high-priority
p99 below low-priority p99**, **tenant isolation** (the abusive tenant
is throttled, its neighbours' requests all resolve), and the runtime
jit cache equals the static census before and after.

``--mode obs`` runs the ISSUE 13 acceptance: with request tracing armed
(``telemetry.enable``, JSONL sink + in-memory collection), a 3-replica
``ServingFleet`` storm absorbs a ``serving.step`` fault burst and a
replica hard-kill, then a ``GenerationServer`` streams sequences
through a ``generate.decode`` burst.  The contract: **0 dropped
accepted requests** on both legs, **every accepted request yields a
complete, correctly-parented span tree** (``telemetry.audit_spans`` —
children contained, durations attributed to within tolerance), fault
firings land as span events, the JSONL export reconstructs the same
clean trees, and the tracing-off path costs **< 5%** of request
latency (per-guard cost × a generous guards-per-request budget vs the
measured untraced per-request latency).  The ISSUE 15 flight leg kills
a traced generation worker mid-step with an unbounded decode fault
storm: the breaker trip must leave a complete flight-recorder bundle
(audit-clean span trees, the fatal ``generate.decode`` firing on
record, a metrics snapshot, compile events == the serving census, and
``recompiles_unexpected == 0``) while every accepted sequence still
resolves explicitly.

``--mode ckpt`` runs the ISSUE 17 acceptance: a subprocess snapshot
storm is SIGKILLed mid-write repeatedly (every committed name must
still pass ``verify_checkpoint`` — atomic commit + fsync means a kill
can truncate only the invisible ``.tmp``), a fault-armed
``BitFlipInjection`` commits a container-consistent but
digest-poisoned snapshot (``verify_checkpoint`` /
``load_snapshot_params`` / ``resume_latest`` must all treat it as
damage), and a live ``WeightUpdater`` streams snapshots under
``keep_last=1`` retention pruning while one mid-stream snapshot is
corrupt.  The contract: **resume always lands on an intact verified
snapshot**, **0 silently-loaded corrupt bytes** (trained on or
served), and **0 dropped rolling updates** — a pruned path is stale
(re-poll), never a skipped snapshot.

``--list-modes`` prints the mode registry and exits.

Exit code 0 on success, 1 on any mismatch.  Forces ``JAX_PLATFORMS=cpu``
(and an 8-device virtual mesh) so it runs anywhere, TPU or not (lint
mode never imports jax at all — mxlint is pure ast).
"""
import argparse
import os
import sys
import tempfile
import time

# must precede any jax import — same bring-up as tests/conftest.py
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def serve_mode(args):
    """Inject-and-drain smoke on the serving runtime (ISSUE 4)."""
    import signal
    import threading

    import jax
    from mxnet_tpu import fault, serving

    rng = np.random.RandomState(0)
    w = rng.randn(8, 4).astype(np.float32)

    @jax.jit
    def mlp(x):
        return x @ w

    def apply(x):
        time.sleep(0.01)           # keep work in flight when SIGTERM lands
        return np.asarray(mlp(x))

    srv = serving.InferenceServer(
        apply, buckets=(1, 2, 4), max_delay=0.002, max_queue=64,
        sample=np.zeros((8,), np.float32),
        breaker=serving.CircuitBreaker(threshold=3, base_delay=0.02,
                                       max_delay=0.1))
    srv.start()
    print(f"[chaos_check] serve: warmed {len(srv.distinct_shapes)} "
          f"bucket executables, ready={srv.ready()}")

    accepted, sheds = [], [0]
    count_lock = threading.Lock()
    stop_submitting = threading.Event()

    def client(k):
        r = np.random.RandomState(k).randn(8).astype(np.float32)
        for i in range(args.requests):
            if stop_submitting.is_set():
                return
            try:
                req = srv.submit(r)
                with count_lock:
                    accepted.append(req)
            except serving.RejectedError:
                with count_lock:
                    sheds[0] += 1
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,)) for k in range(4)]
    with fault.inject("serving.step", RuntimeError("injected step fault"),
                      after_n=5, times=4) as h:
        for t in threads:
            t.start()
        # SIGTERM lands while clients are still submitting and batches are
        # in flight — serve_forever must drain, not drop
        threading.Timer(0.25, os.kill, (os.getpid(), signal.SIGTERM)).start()
        drained = srv.serve_forever(poll=0.01)
    stop_submitting.set()
    for t in threads:
        t.join()

    resolved = sum(1 for r in accepted if r.done())
    oks, errs = 0, 0
    for r in accepted:
        if not r.done():
            continue                 # counted as dropped below — the very
            #                          failure this smoke exists to catch
        if r.exception(timeout=0) is None:
            oks += 1
        else:
            errs += 1
    st = srv.stats
    print(f"[chaos_check] serve: accepted={len(accepted)} ok={oks} "
          f"errored={errs} shed={sheds[0]} injected_fired={h.fired} "
          f"breaker_trips={srv.breaker.trips} stats={st}")
    fails = []
    if not drained:
        fails.append("drain did not complete")
    if resolved != len(accepted):
        fails.append(f"{len(accepted) - resolved} accepted requests were "
                     f"silently dropped")
    if h.fired == 0:
        fails.append("injected step faults never fired")
    if errs == 0:
        fails.append("no request surfaced the injected failure")
    if srv.alive():
        fails.append("batch thread survived the drain")
    if len(st_shapes := srv.distinct_shapes) > 3:
        fails.append(f"bucketing leaked {len(st_shapes)} signatures (> 3)")
    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: drain completed with every accepted "
          f"request resolved ({oks} served, {errs} explicitly errored, "
          f"0 dropped)")
    return 0


def llm_mode(args):
    """Continuous-batching LLM serving chaos (ISSUE 10, sharded gang
    since ISSUE 14): stream generations through a tensor-parallel
    tp=2 server with int8 decode collectives under a decode-fault
    burst + SIGTERM mid-decode."""
    import signal
    import threading

    from mxnet_tpu import fault, serving
    from mxnet_tpu.gluon.model_zoo.causal_lm import (CausalLMConfig,
                                                     init_causal_lm)

    cfg = CausalLMConfig(vocab_size=64, n_layers=2, n_heads=2,
                         head_dim=8, d_ff=32)
    srv = serving.GenerationServer(
        init_causal_lm(cfg, seed=0), cfg,
        buckets=serving.BucketSpec(batch=(1, 2), length=(8, 16)),
        n_slots=4, n_pages=33, page_size=8, max_new_tokens=6,
        max_queue=256, seed=0, tp_shards=2, tp_collectives="int8",
        breaker=serving.CircuitBreaker(threshold=3, base_delay=0.02,
                                       max_delay=0.1),
        name="ChaosGen")
    srv.start()
    census = srv.census()
    warm = srv.jit_cache_count()
    h = srv.healthz()
    print(f"[chaos_check] llm: warmed {warm} executables "
          f"(census {census}: prefill grid + 1 decode) over "
          f"tp_shards={h['tp_shards']} "
          f"({h['tp_collectives']} decode collectives), "
          f"ready={srv.ready()}")

    accepted, sheds = [], [0]
    count_lock = threading.Lock()
    stop_submitting = threading.Event()

    def client(k):
        rng = np.random.RandomState(k)
        for i in range(args.requests):
            if stop_submitting.is_set():
                return
            prompt = rng.randint(0, 64, size=int(rng.randint(1, 15)))
            try:
                req = srv.submit(prompt.astype(np.int32),
                                 max_new_tokens=int(rng.randint(1, 7)),
                                 temperature=float(i % 2), top_k=4)
                with count_lock:
                    accepted.append(req)
            except serving.RejectedError:
                with count_lock:
                    sheds[0] += 1
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(4)]
    with fault.inject("generate.decode",
                      RuntimeError("injected decode fault"),
                      after_n=5, times=3) as h:
        for t in threads:
            t.start()
        # SIGTERM lands while sequences are mid-decode and clients are
        # still submitting — serve_forever must drain, not drop
        threading.Timer(0.3, os.kill, (os.getpid(), signal.SIGTERM)).start()
        drained = srv.serve_forever(poll=0.01)
    stop_submitting.set()
    for t in threads:
        t.join()

    resolved = sum(1 for r in accepted if r.done())
    oks = sum(1 for r in accepted
              if r.done() and r.exception(timeout=0) is None)
    errs = resolved - oks
    st = srv.stats
    print(f"[chaos_check] llm: accepted={len(accepted)} ok={oks} "
          f"errored={errs} shed={sheds[0]} injected_fired={h.fired} "
          f"tokens_out={st['tokens_out']} preempted={st['preempted']} "
          f"stats={st}")
    fails = []
    if not drained:
        fails.append("drain did not complete")
    if resolved != len(accepted):
        fails.append(f"{len(accepted) - resolved} accepted sequences "
                     f"were silently dropped")
    if h.fired == 0:
        fails.append("injected decode faults never fired")
    if errs == 0 and st["tokens_salvaged"] == 0:
        # ISSUE 19: a decode fault SALVAGES in-flight work (bounded
        # budget) — visible as either a budget-exhausted error or
        # salvaged tokens, never as silence
        fails.append("injected failures neither errored nor salvaged "
                     "any sequence")
    if oks == 0:
        fails.append("no sequence was actually served")
    if srv.jit_cache_count() != warm or warm != census:
        fails.append(f"recompile: jit cache {srv.jit_cache_count()} vs "
                     f"warmup {warm} vs census {census}")
    if srv.alloc.free_count() != srv.alloc.allocatable:
        fails.append(f"page leak: {srv.alloc.free_count()} free of "
                     f"{srv.alloc.allocatable} after drain")
    if srv.alive():
        fails.append("decode loop survived the drain")
    fails.extend(_llm_spec_leg(args))
    fails.extend(_llm_salvage_leg(args))
    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: drain completed with every accepted "
          f"sequence resolved ({oks} served, {errs} explicitly errored, "
          f"0 dropped), 0 recompiles ({warm} executables == census), "
          f"pages fully reclaimed; shared-prefix + speculative + "
          f"salvage/journal legs clean")
    return 0


def _llm_spec_leg(args):
    """ISSUE 16 leg: CoW prefix sharing + speculative decoding under
    chaos — 4 clients stream prompts built on ONE common system prompt
    through a speculative server (draft LM proposals, ONE pinned verify
    executable) while a ``generate.decode`` fault burst fires and
    SIGTERM lands mid-decode.  Must hold: 0 dropped accepted sequences,
    ``recompiles_unexpected == 0``, free list == pool after drain.
    Returns failure strings."""
    import signal
    import threading

    from mxnet_tpu import fault, serving
    from mxnet_tpu.gluon.model_zoo.causal_lm import (CausalLMConfig,
                                                     draft_config,
                                                     init_causal_lm)

    cfg = CausalLMConfig(vocab_size=64, n_layers=2, n_heads=2,
                         head_dim=8, d_ff=32)
    dcfg = draft_config(cfg, n_layers=1)
    srv = serving.GenerationServer(
        init_causal_lm(cfg, seed=0), cfg,
        buckets=serving.BucketSpec(batch=(1, 2), length=(16,)),
        n_slots=4, n_pages=65, page_size=4, max_new_tokens=6,
        max_queue=256, seed=0,
        draft=init_causal_lm(dcfg, seed=1), draft_config=dcfg, spec_k=2,
        breaker=serving.CircuitBreaker(threshold=3, base_delay=0.02,
                                       max_delay=0.1),
        name="ChaosSpecGen")
    srv.start()
    census, warm = srv.census(), srv.jit_cache_count()
    print(f"[chaos_check] llm spec leg: warmed {warm} executables "
          f"(census {census}: prefill grid + decode + verify), spec_k=2, "
          f"one system prompt over 4 clients")

    # every client's prompt = the SAME system prompt + a short random
    # tail: the prefix index maps the leading pages once, everyone else
    # shares them (CoW on first divergence)
    system = np.random.RandomState(7).randint(0, 64, size=8) \
        .astype(np.int32)
    accepted, sheds = [], [0]
    lock = threading.Lock()
    stop = threading.Event()

    def client(k):
        rng = np.random.RandomState(200 + k)
        for i in range(args.requests):
            if stop.is_set():
                return
            tail = rng.randint(0, 64,
                               size=int(rng.randint(1, 7))).astype(np.int32)
            try:
                req = srv.submit(np.concatenate([system, tail]),
                                 max_new_tokens=int(rng.randint(1, 7)),
                                 temperature=float(i % 2), top_k=4)
                with lock:
                    accepted.append(req)
            except serving.RejectedError:
                with lock:
                    sheds[0] += 1
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(4)]
    with fault.inject("generate.decode",
                      RuntimeError("injected verify fault"),
                      after_n=5, times=3) as h:
        for t in threads:
            t.start()
        threading.Timer(0.3, os.kill, (os.getpid(), signal.SIGTERM)).start()
        drained = srv.serve_forever(poll=0.01)
    stop.set()
    for t in threads:
        t.join()

    resolved = sum(1 for r in accepted if r.done())
    oks = sum(1 for r in accepted
              if r.done() and r.exception(timeout=0) is None)
    errs = resolved - oks
    st = srv.stats
    recomp = srv.telemetry()["gauges"].get("recompiles_unexpected", 0)
    print(f"[chaos_check] llm spec leg: accepted={len(accepted)} ok={oks} "
          f"errored={errs} shed={sheds[0]} injected_fired={h.fired} "
          f"verify_steps={st['verify_steps']} "
          f"spec_accepted={st['spec_accepted']}/{st['spec_proposed']} "
          f"pages_shared_mapped={st['pages_shared_mapped']} "
          f"cow_faults={st['cow_faults']}")
    fails = []
    if not drained:
        fails.append("spec leg: drain did not complete")
    if resolved != len(accepted):
        fails.append(f"spec leg: {len(accepted) - resolved} accepted "
                     f"sequences were silently dropped")
    if h.fired == 0:
        fails.append("spec leg: injected decode faults never fired")
    if errs == 0 and st["tokens_salvaged"] == 0:
        fails.append("spec leg: injected failures neither errored nor "
                     "salvaged any sequence")
    if oks == 0:
        fails.append("spec leg: no sequence was actually served")
    if st["verify_steps"] == 0:
        fails.append("spec leg: the verify executable never ran")
    if st["pages_shared_mapped"] == 0:
        fails.append("spec leg: the common system prompt never shared a "
                     "page")
    if recomp != 0:
        fails.append(f"spec leg: recompiles_unexpected == {recomp}")
    if srv.jit_cache_count() != warm or warm != census:
        fails.append(f"spec leg: jit cache {srv.jit_cache_count()} vs "
                     f"warmup {warm} vs census {census}")
    if srv.alloc.free_count() != srv.alloc.allocatable:
        fails.append(f"spec leg: page leak — {srv.alloc.free_count()} "
                     f"free of {srv.alloc.allocatable} after drain")
    if srv.alive():
        fails.append("spec leg: decode loop survived the drain")
    return fails


def _llm_salvage_leg(args):
    """ISSUE 19 leg: token-exact preempt/resume under chaos.  Two
    probes: (1) a STARVED pool (two worst-case sequences cannot
    coexist) plus a ``generate.decode`` fault burst — every victim is
    salvaged with its tokens and completes with EXACTLY the stream an
    unfaulted big-pool oracle produces; (2) a sibling process running
    with a decode journal is kill -9'd mid-generation and a fresh
    server restores its in-flight sequences from the journal,
    token-exact.  Must hold: 0 dropped, ``tokens_salvaged > 0``,
    ``journal_restores > 0``, ``recompiles_unexpected == 0``, free
    list == pool.  Returns failure strings."""
    import signal
    import subprocess
    import tempfile

    from mxnet_tpu import fault, serving
    from mxnet_tpu.gluon.model_zoo.causal_lm import (CausalLMConfig,
                                                     init_causal_lm)

    cfg = CausalLMConfig(vocab_size=64, n_layers=2, n_heads=2,
                         head_dim=8, d_ff=32)
    params = init_causal_lm(cfg, seed=0)
    buckets = serving.BucketSpec(batch=(1,), length=(8,))
    prompts = [np.asarray([3, 1, 2], np.int32),
               np.asarray([5, 4], np.int32),
               np.asarray([9, 2, 7], np.int32),
               np.asarray([1, 6], np.int32)]
    kinds = [dict(), dict(temperature=0.9, top_k=6),
             dict(), dict(temperature=0.7, top_k=4)]
    seeds = [11, 22, 33, 44]
    fails = []

    # ---- unfaulted oracle: calm pool, same prompts + explicit seeds
    oracle = serving.GenerationServer(
        params, cfg, buckets=buckets, n_slots=2, n_pages=33,
        page_size=4, max_new_tokens=10, seed=0, name="ChaosSalvOracle")
    oracle.start()
    expected = []
    for p, kw, s in zip(prompts, kinds, seeds):
        expected.append(tuple(int(t) for t in
                              oracle.submit(p, seed=s, **kw).result(60)))
    oracle.drain(30)

    # ---- probe 1: preemption storm + fault burst on a starved pool
    srv = serving.GenerationServer(
        params, cfg, buckets=buckets, n_slots=2, n_pages=5,
        page_size=4, max_new_tokens=10, seed=0, salvage_retries=8,
        breaker=serving.CircuitBreaker(threshold=6, base_delay=0.02,
                                       max_delay=0.1),
        name="ChaosSalvGen")
    srv.start()
    census, warm = srv.census(), srv.jit_cache_count()
    with fault.inject("generate.decode",
                      RuntimeError("injected decode fault"),
                      after_n=3, times=2) as h:
        reqs = [srv.submit(p, seed=s, **kw)
                for p, kw, s in zip(prompts, kinds, seeds)]
        got = [tuple(int(t) for t in r.result(timeout=240)) for r in reqs]
    st = srv.stats
    recomp = srv.telemetry()["gauges"].get("recompiles_unexpected", 0)
    print(f"[chaos_check] llm salvage leg: storm served "
          f"{st['completed']}/{len(prompts)} "
          f"(preempted={st['preempted']} "
          f"tokens_salvaged={st['tokens_salvaged']} "
          f"resumes={st['resumes']} "
          f"salvage_retries={st['salvage_retries']} "
          f"injected_fired={h.fired})")
    if h.fired == 0:
        fails.append("salvage leg: injected decode faults never fired")
    if st["completed"] != len(prompts) or st["failed"] != 0:
        fails.append(f"salvage leg: {st['failed']} sequences failed — "
                     f"salvage dropped accepted work")
    if st["tokens_salvaged"] == 0:
        fails.append("salvage leg: the storm salvaged no tokens")
    if st["preempted"] == 0 or st["resumes"] == 0:
        fails.append("salvage leg: the starved pool never preempted/"
                     "resumed — the storm probe probed nothing")
    if got != expected:
        fails.append("salvage leg: salvaged streams diverge from the "
                     "unfaulted oracle — resume is not token-exact")
    if recomp != 0:
        fails.append(f"salvage leg: recompiles_unexpected == {recomp}")
    if srv.jit_cache_count() != warm or warm != census:
        fails.append(f"salvage leg: jit cache {srv.jit_cache_count()} vs "
                     f"warmup {warm} vs census {census}")
    if srv.alloc.free_count() != srv.alloc.allocatable:
        fails.append(f"salvage leg: page leak — {srv.alloc.free_count()} "
                     f"free of {srv.alloc.allocatable} after drain")
    if not srv.drain(30):
        fails.append("salvage leg: storm server drain did not complete")

    # ---- probe 2: kill -9 mid-generation, restore from the journal
    jdir = tempfile.mkdtemp(prefix="chaos_salvage_")
    jpath = os.path.join(jdir, "decode.jsonl")
    child_src = (
        "import os, sys, time\n"
        "import numpy as np\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from mxnet_tpu import serving\n"
        "from mxnet_tpu.gluon.model_zoo.causal_lm import "
        "CausalLMConfig, init_causal_lm\n"
        "cfg = CausalLMConfig(vocab_size=64, n_layers=2, n_heads=2, "
        "head_dim=8, d_ff=32)\n"
        "srv = serving.GenerationServer(\n"
        "    init_causal_lm(cfg, seed=0), cfg,\n"
        "    buckets=serving.BucketSpec(batch=(1,), length=(8,)),\n"
        "    n_slots=2, n_pages=33, page_size=4, max_new_tokens=32,\n"
        "    seed=0, journal=sys.argv[1], journal_every=1,\n"
        "    name='ChaosJournalGen')\n"
        "srv.start()\n"
        "srv.submit(np.asarray([3, 1, 2], np.int32), seed=11)\n"
        "srv.submit(np.asarray([5, 4], np.int32), temperature=0.9, "
        "top_k=6, seed=22)\n"
        "limit = time.monotonic() + 60\n"
        "while srv.stats['tokens_out'] < 2 "
        "and time.monotonic() < limit:\n"
        "    time.sleep(0.002)\n"
        "print('READY', flush=True)\n"
        "time.sleep(120)\n")
    child = subprocess.Popen([sys.executable, "-c", child_src, jpath],
                             stdout=subprocess.PIPE, text=True)
    ready = False
    line = child.stdout.readline()          # blocks until READY/EOF
    ready = line.strip() == "READY"
    if ready:
        os.kill(child.pid, signal.SIGKILL)  # the actual kill -9
    child.wait()
    if not ready:
        fails.append("salvage leg: journal child never reached READY")
        return fails

    rsrv = serving.GenerationServer(
        params, cfg, buckets=buckets, n_slots=2, n_pages=33,
        page_size=4, max_new_tokens=32, seed=0, name="ChaosRestoreGen")
    rsrv.start()
    exp = [tuple(int(t) for t in
                 rsrv.submit(np.asarray([3, 1, 2], np.int32),
                             seed=11).result(120)),
           tuple(int(t) for t in
                 rsrv.submit(np.asarray([5, 4], np.int32),
                             temperature=0.9, top_k=6,
                             seed=22).result(120))]
    restored = rsrv.restore_journal(jpath)
    outs = sorted(tuple(int(t) for t in r.result(timeout=240))
                  for r in restored.values())
    rst = rsrv.stats
    print(f"[chaos_check] llm salvage leg: kill -9 restore — "
          f"journal_restores={rst['journal_restores']} "
          f"restored={len(restored)} resumes={rst['resumes']}")
    if rst["journal_restores"] == 0 or len(restored) != 2:
        fails.append(f"salvage leg: journal restore recovered "
                     f"{len(restored)} of 2 in-flight sequences")
    if outs != sorted(exp):
        fails.append("salvage leg: restored streams diverge from the "
                     "uninterrupted oracle — journal restore is not "
                     "token-exact")
    if not rsrv.drain(30):
        fails.append("salvage leg: restore server drain did not complete")
    return fails


def _fleet_int8_leg(step, mgr):
    """ISSUE 8 leg: an int8 fleet (per-channel PTQ weights, dequant
    folded into the compiled apply) ingests an f32 training snapshot
    through a rolling update under live traffic — 0 dropped accepted
    requests, executable census unchanged.  Returns failure strings."""
    import threading

    import jax
    import jax.numpy as jnp
    from mxnet_tpu import amp, serving
    from mxnet_tpu.parallel.checkpoint import load_snapshot_params
    from tools.costguard import executable_census

    params, _names = load_snapshot_params(mgr.checkpoints()[-1][1])
    shapes = [tuple(p.shape) for p in params]
    iw1, ib1 = shapes.index((16, 8)), shapes.index((16,))
    iw2, ib2 = shapes.index((4, 16)), shapes.index((4,))
    quant = amp.Int8Quantizer(axis=0)      # (units, in_units) kernels

    def fwd(p, x):
        h = jnp.maximum(x @ p[iw1].T + p[ib1], 0.0)
        return h @ p[iw2].T + p[ib2]

    qfn = jax.jit(quant.wrap(fwd))
    fleet = serving.ServingFleet.replicated(
        qfn, quant.quantize([jnp.asarray(p) for p in params]), 3,
        quantizer=quant.quantize, buckets=(1, 2, 4), max_delay=0.002,
        sample=np.ones((8,), np.float32), name="ChaosInt8Fleet")
    fleet.start()
    census = executable_census(fleet.buckets)
    updater = serving.WeightUpdater(fleet, mgr, poll=0.02).start()
    n_int8 = sum(1 for p in fleet.replicas[0].apply.params
                 if p.dtype == jnp.int8)
    print(f"[chaos_check] int8 fleet: 3 replicas up, census={census}, "
          f"{n_int8} int8 weight payload(s) served")

    accepted, sheds = [], [0]
    lock = threading.Lock()
    stop = threading.Event()

    def client(k):
        r = np.random.RandomState(100 + k).randn(8).astype(np.float32)
        while not stop.is_set():
            try:
                req = fleet.submit(r)
                with lock:
                    accepted.append(req)
            except serving.RejectedError:
                with lock:
                    sheds[0] += 1
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,)) for k in range(2)]
    for t in threads:
        t.start()
    fails = []
    try:
        time.sleep(0.1)
        # one more f32 training step -> a fresh f32 snapshot the int8
        # fleet must re-quantize on ingest
        rng = np.random.RandomState(42)
        step(rng.randn(16, 8).astype(np.float32),
             rng.randint(0, 4, (16,)))
        mgr.save()
        t0 = time.time()
        while updater.applied < 1 and time.time() - t0 < 30:
            time.sleep(0.01)
        if updater.applied < 1:
            fails.append(f"int8 fleet: f32 snapshot did not roll out "
                         f"within 30s (applied={updater.applied}, "
                         f"skipped={updater.skipped})")
        time.sleep(0.1)
    finally:
        stop.set()
        for t in threads:
            t.join()
        updater.stop(timeout=10)
        drained = fleet.drain(timeout=30)
    resolved = sum(1 for r in accepted if r.done())
    errs = [r.exception(0) for r in accepted
            if r.done() and r.exception(0) is not None]
    print(f"[chaos_check] int8 fleet: accepted={len(accepted)} "
          f"resolved={resolved} errored={len(errs)} shed={sheds[0]} "
          f"swaps={fleet.stats['swaps']} jit_cache={qfn._cache_size()}")
    if not drained:
        fails.append("int8 fleet: drain did not complete")
    if resolved != len(accepted):
        fails.append(f"int8 fleet: {len(accepted) - resolved} accepted "
                     f"requests dropped")
    if errs:
        fails.append(f"int8 fleet: {len(errs)} accepted requests errored "
                     f"(first: {errs[0]!r})")
    if qfn._cache_size() > census:
        fails.append(f"int8 fleet: recompile leak — jit cache "
                     f"{qfn._cache_size()} > census {census}")
    if n_int8 != 2:        # both Dense kernels; biases stay f32
        fails.append(f"int8 fleet: expected 2 int8 weight payloads, "
                     f"served {n_int8}")
    # the rolled-out weights are the NEW snapshot's, re-quantized
    new_params, _ = load_snapshot_params(mgr.checkpoints()[-1][1])
    ref = quant.dequantize(quant.quantize(
        [jnp.asarray(p) for p in new_params]))
    x1 = np.ones((1, 8), np.float32)
    want = np.asarray(fwd([np.asarray(r) for r in ref], x1))[0]
    got = np.asarray(fleet.replicas[0].apply(x1))[0]
    if not np.allclose(got, want, atol=1e-5):
        fails.append("int8 fleet: replica 0 does not serve the "
                     "re-quantized final snapshot")
    return fails


def fleet_mode(args):
    """Replica-kill + rolling-update + SIGTERM smoke (ISSUE 7)."""
    import signal
    import tempfile as _tempfile
    import threading

    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import fault, gluon, parallel, serving
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.checkpoint import (CheckpointManager,
                                               load_snapshot_params)
    from tools.costguard import executable_census

    # -- a real training job feeding the snapshot stream -------------------
    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8),
            nn.Dense(4, in_units=16))
    net.initialize()
    mesh = parallel.make_mesh(dp=len(jax.devices()))
    step = parallel.TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              mx.optimizer.create("adam"), mesh=mesh)
    rng = np.random.RandomState(0)
    batches = [(rng.randn(16, 8).astype(np.float32),
                rng.randint(0, 4, (16,))) for _ in range(6)]
    d = _tempfile.mkdtemp(prefix="chaos_fleet_")
    mgr = CheckpointManager(step, d, keep_last=5)
    for x, y in batches[:2]:
        step(x, y)
    mgr.save()
    params, names = load_snapshot_params(mgr.checkpoints()[-1][1])
    first_seen = mgr.checkpoints()[-1][0]

    # -- the serving side: one shared jitted forward, 3 hot-swap replicas --
    shapes = [tuple(p.shape) for p in params]
    iw1, ib1 = shapes.index((16, 8)), shapes.index((16,))
    iw2, ib2 = shapes.index((4, 16)), shapes.index((4,))
    traces = []

    @jax.jit
    def fwd(p, x):
        traces.append(x.shape)
        h = jnp.maximum(x @ p[iw1].T + p[ib1], 0.0)
        return h @ p[iw2].T + p[ib2]

    class KillableApply(serving.HotSwapApply):
        def __init__(self, params):
            super().__init__(lambda p, x: np.asarray(fwd(p, x)), params)
            self.dead = False

        def __call__(self, *leaves):
            if self.dead:
                raise SystemExit("replica killed")
            time.sleep(0.003)          # keep work in flight at kill time
            return super().__call__(*leaves)

    applies = [KillableApply(list(params)) for _ in range(3)]
    fleet = serving.ServingFleet(
        applies, buckets=(1, 2, 4), max_delay=0.002,
        sample=np.ones((8,), np.float32), name="ChaosFleet")
    fleet.start()
    census = executable_census(fleet.buckets)
    warm = len(set(traces))
    print(f"[chaos_check] fleet: 3 replicas warm, census={census} "
          f"compiled={warm} jit_cache={fwd._cache_size()} "
          f"ready={fleet.ready()}")

    updater = serving.WeightUpdater(fleet, mgr, last_seen=first_seen,
                                    poll=0.02)
    updater.start()

    accepted, sheds = [], [0]
    count_lock = threading.Lock()
    stop_submitting = threading.Event()

    def client(k):
        r = np.random.RandomState(k).randn(8).astype(np.float32)
        while not stop_submitting.is_set():
            try:
                req = fleet.submit(r)
                with count_lock:
                    accepted.append(req)
            except serving.RejectedError:
                with count_lock:
                    sheds[0] += 1
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    fails = []
    try:
        time.sleep(0.15)
        applies[1].dead = True         # hard-kill replica 1 under traffic
        time.sleep(0.15)
        for round_no in (1, 2):        # stream two snapshots through
            for x, y in batches[2 * round_no:2 * round_no + 2]:
                step(x, y)
            mgr.save()
            t0 = time.time()
            while updater.applied < round_no and time.time() - t0 < 30:
                time.sleep(0.01)
            if updater.applied < round_no:
                fails.append(f"rolling update {round_no} did not apply "
                             f"within 30s (applied={updater.applied}, "
                             f"skipped={updater.skipped})")
        # SIGTERM lands while clients are still submitting
        threading.Timer(0.1, os.kill, (os.getpid(), signal.SIGTERM)).start()
        drained = fleet.serve_forever(poll=0.01)
    finally:
        stop_submitting.set()
        for t in threads:
            t.join()
        updater.stop(timeout=10)

    resolved = sum(1 for r in accepted if r.done())
    errs = [r.exception(0) for r in accepted
            if r.done() and r.exception(0) is not None]
    st = fleet.stats
    print(f"[chaos_check] fleet: accepted={len(accepted)} "
          f"resolved={resolved} errored={len(errs)} shed={sheds[0]} "
          f"redispatched={st['redispatched']} swaps={st['swaps']} "
          f"probes={st['probes']} compiled={len(set(traces))} "
          f"jit_cache={fwd._cache_size()}")
    if not drained:
        fails.append("fleet drain did not complete")
    if resolved != len(accepted):
        fails.append(f"{len(accepted) - resolved} accepted requests were "
                     f"silently dropped")
    if errs:
        fails.append(f"{len(errs)} accepted requests errored — failover "
                     f"should have served them (first: {errs[0]!r})")
    if st["redispatched"] < 1:
        fails.append("the replica kill never exercised failover")
    if updater.applied != 2:
        fails.append(f"expected 2 applied rolling updates, got "
                     f"{updater.applied}")
    if len(set(traces)) > census or fwd._cache_size() > census:
        fails.append(f"recompile leak: {len(set(traces))} traced / "
                     f"{fwd._cache_size()} cached > census {census}")
    if fleet.alive():
        fails.append("a replica batch thread survived the drain")
    # the survivors must actually serve the LAST snapshot's weights
    want = np.asarray(fwd([jnp.asarray(p) for p in
                           load_snapshot_params(mgr.checkpoints()[-1][1])[0]],
                          np.ones((1, 8), np.float32)))[0]
    got = np.asarray(applies[0](np.ones((1, 8), np.float32)))[0]
    if not np.allclose(got, want):
        fails.append("replica 0 does not serve the final snapshot weights")
    # ISSUE 8 leg: f32 snapshot -> int8 fleet rolling update
    fails += _fleet_int8_leg(step, mgr)
    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: replica kill + 2 rolling updates + SIGTERM "
          f"with 0 dropped accepted requests, 0 recompiles "
          f"({len(set(traces))}/{census} executables), "
          f"{st['redispatched']} failovers; int8-fleet f32-snapshot "
          f"rolling update clean")
    return 0


def _slo_fleet_leg():
    """The fleet half of the SLO storm: gold/bronze replica groups, an
    abusive tenant, a replica kill, one autoscale up/down cycle, and a
    rolling weight update — concurrently.  Returns failure strings."""
    import threading

    import jax
    from mxnet_tpu import profiler, serving

    W = np.eye(4, dtype=np.float32)

    @jax.jit
    def fwd(params, x):
        (w,) = params
        return x @ w

    class KillableApply(serving.HotSwapApply):
        def __init__(self, delay):
            super().__init__(lambda p, x: np.asarray(fwd(p, x)), [W])
            self.dead = False
            self.delay = delay

        def __call__(self, *leaves):
            if self.dead:
                raise SystemExit("replica killed")
            time.sleep(self.delay)
            return super().__call__(*leaves)

    qos = serving.TenantQoS(
        classes=[serving.QoSClass("gold", priority=10, deadline=5.0,
                                  group="gold"),
                 serving.QoSClass("bronze", priority=0, deadline=5.0,
                                  admit_frac=0.8, group="bronze")],
        default_class="bronze", tenant_rate=200, tenant_burst=200)
    gold = [KillableApply(0.001)]
    bronze = [KillableApply(0.004) for _ in range(2)]
    fleet = serving.ServingFleet(
        {"gold": gold, "bronze": bronze}, buckets=(1, 2, 4),
        max_delay=0.002, max_inflight=16, qos=qos,
        sample=np.ones((4,), np.float32), name="SloFleet")
    fleet.start()
    census = fleet.grid_census
    warm = fwd._cache_size()
    scaler = serving.FleetAutoscaler(
        fleet, serving.ScalingPolicy(
            min_replicas=1, max_replicas=3, up_occupancy=0.25,
            down_occupancy=0.1, up_queue_depth=4, up_ticks=2,
            down_ticks=10, cooldown=0.1),
        group="bronze", tick=0.02, watchdog_secs=60).start()
    updater = serving.WeightUpdater(fleet)
    print(f"[chaos_check] slo fleet: groups gold=1 bronze=2, census="
          f"{census}, autoscaler on bronze, ready={fleet.ready()}")

    stop = threading.Event()
    lock = threading.Lock()
    served = {}                 # tenant -> [accepted Requests]
    throttled = {}              # tenant -> count

    def client(tenant, klass, pause):
        x = np.random.RandomState(hash(tenant) % 97).randn(4) \
            .astype(np.float32)
        while not stop.is_set():
            try:
                r = fleet.submit(x, tenant=tenant, klass=klass)
                with lock:
                    served.setdefault(tenant, []).append(r)
            except serving.TenantThrottledError:
                with lock:
                    throttled[tenant] = throttled.get(tenant, 0) + 1
            except serving.RejectedError:
                pass
            time.sleep(pause)

    specs = [("g0", "gold", 0.008), ("g1", "gold", 0.008),
             ("b0", "bronze", 0.008), ("b1", "bronze", 0.008),
             ("abuser", "bronze", 0.0005)]    # ~2000/s — way over rate
    threads = [threading.Thread(target=client, args=s) for s in specs]
    for t in threads:
        t.start()
    fails = []
    try:
        time.sleep(0.3)
        bronze[1].dead = True       # replica kill mid-storm
        # rolling weight update mid-storm (validated, quarantine→swap→
        # probe→readmit per replica, autoscaler racing on bronze).  The
        # updater skips dead/retired replicas; a kill that has not hit a
        # batch yet can still race the roll, so one retry is legitimate
        # (the real WeightUpdater watch loop re-polls the same way).
        try:
            updater.update([2.0 * W])
        except serving.UpdateRolledBackError:
            updater.update([2.0 * W])
        t0 = time.time()
        while scaler.stats["scale_ups"] < 1 and time.time() - t0 < 30:
            time.sleep(0.02)
        time.sleep(0.3)             # let the scaled fleet absorb the storm
    finally:
        stop.set()
        for t in threads:
            t.join()
    # storm over: the autoscaler should give the capacity back
    t0 = time.time()
    while scaler.stats["scale_downs"] < 1 and time.time() - t0 < 30:
        time.sleep(0.05)
    scaler.stop(timeout=10)
    drained = fleet.drain(timeout=30)
    classes = fleet.healthz()["classes"]
    all_reqs = [r for reqs in served.values() for r in reqs]
    resolved = sum(1 for r in all_reqs if r.done())
    errs = [r.exception(0) for r in all_reqs
            if r.done() and r.exception(0) is not None]
    st = scaler.stats
    print(f"[chaos_check] slo fleet: accepted={len(all_reqs)} "
          f"resolved={resolved} errored={len(errs)} "
          f"throttled={throttled} scale={st} "
          f"gold_p99={classes['gold']['p99_ms']} "
          f"bronze_p99={classes['bronze']['p99_ms']} "
          f"jit_cache={fwd._cache_size()}")
    if not drained:
        fails.append("slo fleet: drain did not complete")
    if resolved != len(all_reqs):
        fails.append(f"slo fleet: {len(all_reqs) - resolved} accepted "
                     f"requests silently dropped")
    if errs:
        fails.append(f"slo fleet: {len(errs)} accepted requests errored "
                     f"(first: {errs[0]!r})")
    if throttled.get("abuser", 0) < 10:
        fails.append(f"slo fleet: abusive tenant was not throttled "
                     f"({throttled})")
    for tenant in ("g0", "g1", "b0", "b1"):
        if throttled.get(tenant, 0) > 0:
            fails.append(f"slo fleet: well-behaved tenant {tenant} was "
                         f"throttled {throttled[tenant]}x — isolation "
                         f"failed")
        if not served.get(tenant):
            fails.append(f"slo fleet: tenant {tenant} had nothing served")
    if not (classes["gold"]["p99_ms"] < classes["bronze"]["p99_ms"]):
        fails.append(f"slo fleet: per-class p99 ordering failed "
                     f"(gold {classes['gold']['p99_ms']} ms >= bronze "
                     f"{classes['bronze']['p99_ms']} ms)")
    if st["scale_ups"] < 1 or st["scale_downs"] < 1:
        fails.append(f"slo fleet: no full autoscale cycle ({st})")
    if updater.applied != 1:
        fails.append(f"slo fleet: rolling update did not apply "
                     f"({updater.applied})")
    if not np.allclose(np.asarray(gold[0](np.ones((1, 4), np.float32)))[0],
                       2.0 * np.ones(4, np.float32)):
        fails.append("slo fleet: gold replica does not serve the rolled "
                     "weights")
    if fwd._cache_size() != warm or warm > census:
        fails.append(f"slo fleet: recompile — jit cache "
                     f"{fwd._cache_size()} vs warm {warm} vs census "
                     f"{census}")
    # (r1's fate depends on which bronze replica the scaler retired —
    # either way the counter-leak sweep below proves membership
    # accounting held)
    leaked = [s for s in profiler.counters("SloFleet-r").keys()
              if s.split("::")[0].replace("SloFleet-r", "") not in
              {str(rep.index) for rep in fleet.replicas}]
    if leaked:
        fails.append(f"slo fleet: retired replicas leaked counter "
                     f"series: {leaked}")
    return fails


def _slo_llm_leg():
    """The generation half: a disaggregated server (prefill worker
    group + handoff) under a long-prefill + decode mix with two
    priority classes.  Returns failure strings."""
    import threading

    from mxnet_tpu import serving
    from mxnet_tpu.gluon.model_zoo.causal_lm import (CausalLMConfig,
                                                     init_causal_lm)

    cfg = CausalLMConfig(vocab_size=64, n_layers=2, n_heads=2,
                         head_dim=8, d_ff=32)
    qos = serving.TenantQoS(
        classes=[serving.QoSClass("gold", priority=10, deadline=20.0),
                 serving.QoSClass("bronze", priority=0, deadline=20.0,
                                  admit_frac=0.5)],
        default_class="bronze")
    srv = serving.GenerationServer(
        init_causal_lm(cfg, seed=0), cfg,
        buckets=serving.BucketSpec(batch=(1, 2), length=(8, 32)),
        n_slots=2, n_pages=41, page_size=8, max_new_tokens=8,
        max_queue=128, seed=0, prefill_workers=2, qos=qos,
        name="SloGen")
    srv.start()
    census, warm = srv.census(), srv.jit_cache_count()
    print(f"[chaos_check] slo llm: disaggregated (2 prefill workers), "
          f"census={census} (grid + handoff + decode), warmed {warm}")

    stop = threading.Event()
    lock = threading.Lock()
    accepted = {"gold": [], "bronze": []}

    def client(k, klass, long_prompts, pause):
        rng = np.random.RandomState(k)
        while not stop.is_set():
            if long_prompts:
                n, new = int(rng.randint(24, 31)), int(rng.randint(5, 9))
            else:
                n, new = int(rng.randint(1, 8)), int(rng.randint(1, 4))
            try:
                r = srv.submit(rng.randint(0, 64, size=n).astype(np.int32),
                               max_new_tokens=new,
                               tenant=f"t{k}", klass=klass)
                with lock:
                    accepted[klass].append(r)
            except serving.RejectedError:
                pass
            time.sleep(pause)

    # three bronze clients streaming LONG prompts oversubscribe the two
    # decode slots (a deep low-priority queue); gold's short prompts
    # must jump it — the per-class p99 ordering under exactly the
    # long-prefill interference this mode exists to check
    threads = [threading.Thread(target=client, args=(k, klass, lng, p))
               for k, (klass, lng, p) in enumerate(
                   [("gold", False, 0.01), ("bronze", True, 0.001),
                    ("bronze", True, 0.001), ("bronze", True, 0.001)])]
    for t in threads:
        t.start()
    time.sleep(1.2)
    stop.set()
    for t in threads:
        t.join()
    drained = srv.drain(timeout=60)
    classes = srv.healthz()["classes"]
    fails = []
    all_reqs = accepted["gold"] + accepted["bronze"]
    resolved = sum(1 for r in all_reqs if r.done())
    oks = sum(1 for r in all_reqs
              if r.done() and r.exception(0) is None)
    print(f"[chaos_check] slo llm: accepted={len(all_reqs)} "
          f"resolved={resolved} ok={oks} "
          f"gold_p99={classes['gold']['p99_ms']} "
          f"bronze_p99={classes['bronze']['p99_ms']} "
          f"handoffs={srv.stats['handoffs']} "
          f"jit_cache={srv.jit_cache_count()}")
    if not drained:
        fails.append("slo llm: drain did not complete")
    if resolved != len(all_reqs):
        fails.append(f"slo llm: {len(all_reqs) - resolved} accepted "
                     f"sequences silently dropped")
    if oks == 0 or not accepted["gold"] or not accepted["bronze"]:
        fails.append("slo llm: traffic did not actually flow")
    if srv.stats["handoffs"] < 1:
        fails.append("slo llm: no prefill→decode handoff happened — the "
                     "disaggregated path was not exercised")
    if srv.jit_cache_count() != warm or warm != census:
        fails.append(f"slo llm: recompile — jit cache "
                     f"{srv.jit_cache_count()} vs warm {warm} vs census "
                     f"{census}")
    if srv.alloc.free_count() != srv.alloc.allocatable:
        fails.append(f"slo llm: page leak ({srv.alloc.free_count()} of "
                     f"{srv.alloc.allocatable} free)")
    if not (classes["gold"]["p99_ms"] < classes["bronze"]["p99_ms"]):
        fails.append(f"slo llm: per-class p99 ordering failed (gold "
                     f"{classes['gold']['p99_ms']} ms >= bronze "
                     f"{classes['bronze']['p99_ms']} ms)")
    return fails


def _obs_fleet_leg():
    """The fleet half of the observability storm: a traced 3-replica
    fleet under client traffic with a ``serving.step`` fault burst and
    one replica hard-killed — every accepted request must resolve AND
    yield a complete, attribution-clean span tree.  Returns (failure
    strings, accepted count)."""
    import threading

    import jax
    from mxnet_tpu import fault, serving, telemetry

    W = np.eye(4, dtype=np.float32)

    @jax.jit
    def fwd(params, x):
        (w,) = params
        return x @ w

    class KillableApply(serving.HotSwapApply):
        def __init__(self):
            super().__init__(lambda p, x: np.asarray(fwd(p, x)), [W])
            self.dead = False

        def __call__(self, *leaves):
            if self.dead:
                raise SystemExit("replica killed")
            time.sleep(0.002)      # keep work in flight at kill time
            return super().__call__(*leaves)

    applies = [KillableApply() for _ in range(3)]
    fleet = serving.ServingFleet(
        applies, buckets=(1, 2, 4), max_delay=0.002,
        sample=np.ones((4,), np.float32), name="ObsFleet")
    fleet.start()

    accepted, sheds = [], [0]
    count_lock = threading.Lock()
    stop_submitting = threading.Event()

    def client(k):
        r = np.random.RandomState(k).randn(4).astype(np.float32)
        while not stop_submitting.is_set():
            try:
                req = fleet.submit(r)
                with count_lock:
                    accepted.append(req)
            except serving.RejectedError:
                with count_lock:
                    sheds[0] += 1
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(4)]
    fails = []
    for t in threads:
        t.start()
    try:
        time.sleep(0.1)
        # a fault burst the failover path absorbs — firings must land
        # as span events on the in-flight step spans
        with fault.inject("serving.step", RuntimeError("injected storm"),
                          times=3):
            time.sleep(0.15)
        applies[1].dead = True     # hard-kill replica 1 under traffic
        time.sleep(0.2)
    finally:
        stop_submitting.set()
        for t in threads:
            t.join()
    fleet.drain()

    unresolved = sum(1 for r in accepted if not r.done())
    errs = [r.exception(0) for r in accepted
            if r.done() and r.exception(0) is not None]
    if unresolved:
        fails.append(f"obs fleet: {unresolved} accepted requests were "
                     f"silently dropped")
    if errs:
        fails.append(f"obs fleet: {len(errs)} accepted requests errored "
                     f"— failover should have absorbed the chaos "
                     f"(first: {errs[0]!r})")

    traces = telemetry.finished_traces(clear=True)
    if len(traces) != len(accepted):
        fails.append(f"obs fleet: {len(accepted)} accepted requests but "
                     f"{len(traces)} span trees — tracing is lossy")
    bad = 0
    fault_events = 0
    failovers = 0
    for tr in traces:
        problems = telemetry.audit_spans(tr)
        if problems:
            bad += 1
            if bad == 1:
                fails.append(f"obs fleet: incomplete/mis-attributed span "
                             f"tree {tr.trace_id}: {problems}")
        for sp in tr.spans:
            failovers += sp.name == "failover"
            fault_events += sum(1 for ev in sp.events
                                if ev["name"] == "fault")
    if bad > 1:
        fails.append(f"obs fleet: {bad} of {len(traces)} span trees "
                     f"failed the audit")
    if fault_events < 1:
        fails.append("obs fleet: the injected fault burst left no span "
                     "events — fault.fire observer not wired")
    if failovers < 1:
        fails.append("obs fleet: the replica kill produced no failover "
                     "spans")
    st = fleet.stats
    print(f"[chaos_check] obs fleet: accepted={len(accepted)} "
          f"shed={sheds[0]} trees={len(traces)} audit_bad={bad} "
          f"failover_spans={failovers} fault_events={fault_events} "
          f"redispatched={st['redispatched']}")
    return fails, len(accepted)


def _obs_llm_leg():
    """The generation half: a traced ``GenerationServer`` streams
    sequences through a ``generate.decode`` fault burst — accepted
    sequences resolve (tokens or explicit error) and every one yields a
    complete queue→prefill→decode span tree."""
    import threading

    from mxnet_tpu import fault, serving, telemetry
    from mxnet_tpu.gluon.model_zoo.causal_lm import (CausalLMConfig,
                                                     init_causal_lm)

    cfg = CausalLMConfig(vocab_size=48, n_layers=2, n_heads=2,
                         head_dim=8, d_ff=32)
    params = init_causal_lm(cfg, seed=3)
    srv = serving.GenerationServer(
        params, cfg, buckets=serving.BucketSpec(batch=(1,), length=(8,)),
        n_slots=2, n_pages=17, page_size=4, max_new_tokens=6, seed=0,
        name="ObsGen")
    srv.start()

    accepted = []
    count_lock = threading.Lock()
    fails = []

    def client(k):
        rng = np.random.RandomState(k)
        for _ in range(4):
            prompt = rng.randint(1, 40, (3,)).astype(np.int32)
            try:
                req = srv.submit(prompt, max_new_tokens=4)
                with count_lock:
                    accepted.append(req)
            except serving.RejectedError:
                pass
            time.sleep(0.01)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    with fault.inject("generate.decode", RuntimeError("decode storm"),
                      times=2):
        for t in threads:
            t.join()
        srv.drain()

    unresolved = sum(1 for r in accepted if not r.done())
    if unresolved:
        fails.append(f"obs llm: {unresolved} accepted sequences were "
                     f"silently dropped")
    traces = telemetry.finished_traces(clear=True)
    if len(traces) != len(accepted):
        fails.append(f"obs llm: {len(accepted)} accepted sequences but "
                     f"{len(traces)} span trees")
    bad = 0
    for tr in traces:
        problems = telemetry.audit_spans(tr)
        if problems:
            bad += 1
            if bad == 1:
                fails.append(f"obs llm: bad span tree {tr.trace_id}: "
                             f"{problems}")
        names = {sp.name for sp in tr.spans}
        if not {"admit", "queue", "prefill"} <= names:
            fails.append(f"obs llm: trace {tr.trace_id} is missing "
                         f"generation phases ({sorted(names)})")
            break
    if bad > 1:
        fails.append(f"obs llm: {bad} of {len(traces)} span trees "
                     f"failed the audit")
    errored = sum(1 for r in accepted
                  if r.done() and r.exception(0) is not None)
    print(f"[chaos_check] obs llm: accepted={len(accepted)} "
          f"errored_explicitly={errored} trees={len(traces)} "
          f"audit_bad={bad}")
    return fails


def _obs_flight_leg():
    """The crash flight recorder (ISSUE 15): a traced generation worker
    is killed mid-step by a decode fault storm that trips the breaker —
    the breaker-OPEN trigger must leave a complete post-mortem bundle
    (audit-clean span trees, the fatal fault firing on record,
    ``recompiles_unexpected == 0``) and every accepted sequence must
    still resolve explicitly.  Returns failure strings."""
    import json as _json
    import tempfile as _tempfile
    import threading

    from mxnet_tpu import fault, serving, telemetry
    from mxnet_tpu.gluon.model_zoo.causal_lm import (CausalLMConfig,
                                                     init_causal_lm)

    d = _tempfile.mkdtemp(prefix="chaos_flight_")
    telemetry.enable_flight(directory=d, limit=4096)
    fails = []
    cfg = CausalLMConfig(vocab_size=48, n_layers=2, n_heads=2,
                         head_dim=8, d_ff=32)
    srv = serving.GenerationServer(
        init_causal_lm(cfg, seed=5), cfg,
        buckets=serving.BucketSpec(batch=(1,), length=(8,)),
        n_slots=2, n_pages=17, page_size=4, max_new_tokens=6, seed=0,
        # threshold=1: the FIRST mid-step death trips OPEN (prefill
        # successes interleave with decode failures, so a higher
        # threshold never sees consecutive ones on this tiny model)
        breaker=serving.CircuitBreaker(threshold=1, base_delay=0.5),
        name="FlightGen")
    try:
        srv.start()       # traced warmup: compile events == census

        accepted = []
        count_lock = threading.Lock()

        def client(k):
            rng = np.random.RandomState(k)
            for _ in range(3):
                try:
                    req = srv.submit(rng.randint(1, 40, (3,))
                                     .astype(np.int32), max_new_tokens=4)
                    with count_lock:
                        accepted.append(req)
                except (serving.RejectedError, serving.ServerClosedError):
                    pass
                time.sleep(0.01)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(3)]
        # an unbounded decode fault storm armed BEFORE traffic (two
        # clean steps, then every decode step fails): the worker dies
        # mid-generation and keeps dying until the breaker trips OPEN —
        # THE mid-step kill the recorder exists for
        with fault.inject("generate.decode",
                          RuntimeError("decode storm — worker killed"),
                          after_n=2):
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            deadline = time.monotonic() + 15
            while srv.breaker.state_code() != 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
    finally:
        srv.drain()

    unresolved = sum(1 for r in accepted if not r.done())
    if unresolved:
        fails.append(f"obs flight: {unresolved} accepted sequences were "
                     f"silently dropped")
    bundle = telemetry.flight().last_path
    if bundle is None:
        fails.append("obs flight: the breaker trip left no "
                     "flight-recorder bundle")
        telemetry.flight().enabled = False
        return fails
    bad = telemetry.audit_jsonl(bundle)
    if bad:
        tid, problems = next(iter(bad.items()))
        fails.append(f"obs flight: bundle has {len(bad)} bad span trees "
                     f"(e.g. {tid}: {problems})")
    with open(bundle) as f:
        recs = [_json.loads(line) for line in f if line.strip()]
    header = recs[0]
    if header.get("kind") != "flight" \
            or header.get("reason") != "breaker-open":
        fails.append(f"obs flight: bundle header is {header.get('kind')}/"
                     f"{header.get('reason')}, expected a breaker-open "
                     f"dump")
    fatal = [r for r in recs if r.get("kind") == "fault"
             and r.get("name") == "generate.decode"]
    if not fatal:
        fails.append("obs flight: the fatal generate.decode firing is "
                     "not in the bundle")
    if not any(r.get("kind") == "metrics" for r in recs):
        fails.append("obs flight: bundle carries no metrics snapshot")
    cs = telemetry.compile_site_stats("FlightGen")
    if cs["unexpected"] != 0:
        fails.append(f"obs flight: {cs['unexpected']} unexpected "
                     f"recompiles (must be 0)")
    if cs["misses"] != srv.census():
        fails.append(f"obs flight: {cs['misses']} compile events != "
                     f"census {srv.census()}")
    telemetry.flight().enabled = False
    print(f"[chaos_check] obs flight: accepted={len(accepted)} "
          f"bundle={os.path.basename(bundle)} records={len(recs)} "
          f"fault_recs={len(fatal)} compile_events={cs['misses']} "
          f"census={srv.census()} recompiles_unexpected="
          f"{cs['unexpected']}")
    return fails


def _obs_overhead_leg():
    """The off-switch bound: with telemetry disabled, the serving stack
    pays one module-attribute read + branch per instrumentation site.
    A/B wall-clock on a storm workload is hopelessly noisy at smoke
    scale, so the bound is measured deterministically: per-guard cost ×
    a generous guards-per-request budget must stay under 5% of the
    measured per-request latency of an untraced server."""
    import jax
    from mxnet_tpu import serving, telemetry

    telemetry.disable()

    @jax.jit
    def f(x):
        return x * 2.0

    srv = serving.InferenceServer(
        lambda x: np.asarray(f(x)), buckets=(1, 2, 4), max_delay=0.002,
        sample=np.zeros((3,), np.float32), name="ObsBase")
    srv.start()
    n, wave = 200, 50                # waves stay inside the admit queue
    t0 = time.perf_counter()
    for lo in range(0, n, wave):
        reqs = [srv.submit(np.full((3,), float(i % 7), np.float32))
                for i in range(lo, lo + wave)]
        for r in reqs:
            r.result(30)
    per_request = (time.perf_counter() - t0) / n
    srv.drain()

    per_guard = telemetry.guard_cost()
    # every instrumentation site on the longest path (admit, offer,
    # queue pop, coalesce, step, resolution, done-callback…) is well
    # under this budget
    guards_per_request = 64
    frac = per_guard * guards_per_request / per_request
    print(f"[chaos_check] obs overhead: per_guard={per_guard * 1e9:.1f}ns "
          f"x {guards_per_request} guards vs per_request="
          f"{per_request * 1e6:.0f}us -> {frac * 100:.3f}% (< 5% required)")
    if frac >= 0.05:
        return [f"obs overhead: off-switch costs {frac * 100:.2f}% of "
                f"request latency (>= 5%)"]
    return []


def obs_mode(args):
    """Traced storm + replica kill + fault burst: zero dropped accepted
    requests, 100% complete span trees, attribution within tolerance,
    JSONL export audit-clean, off-switch overhead bounded (ISSUE 13)."""
    import tempfile as _tempfile

    from mxnet_tpu import telemetry

    d = _tempfile.mkdtemp(prefix="chaos_obs_")
    sink_path = os.path.join(d, "spans.jsonl")
    telemetry.enable(sample=1.0, sink=sink_path, collect=True,
                     collect_limit=65536)
    try:
        fails, n_fleet = _obs_fleet_leg()
        fails += _obs_llm_leg()
        fails += _obs_flight_leg()
    finally:
        telemetry.disable()
        telemetry.config().sink.close()
        telemetry.config().sink = None
        telemetry.flight().enabled = False
    # the JSONL export must reconstruct to the same clean trees
    bad_jsonl = telemetry.audit_jsonl(sink_path)
    n_exported = len(telemetry.read_spans(sink_path))
    if bad_jsonl:
        tid, problems = next(iter(bad_jsonl.items()))
        fails.append(f"obs: JSONL round-trip has {len(bad_jsonl)} bad "
                     f"trees (e.g. {tid}: {problems})")
    fails += _obs_overhead_leg()
    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: traced storm survived — 0 dropped "
          f"accepted requests, 100% complete span trees on all legs "
          f"({n_exported} trees exported + JSONL audit clean), "
          f"attribution within tolerance, breaker-trip flight bundle "
          f"audit-clean with 0 unexpected recompiles, off-switch "
          f"overhead < 5%")
    return 0


def slo_mode(args):
    """Mixed-tenant SLO storm + replica kill + autoscale cycle +
    rolling update, then the disaggregated-generation leg (ISSUE 12)."""
    fails = _slo_fleet_leg()
    fails += _slo_llm_leg()
    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print("[chaos_check] PASS: mixed-tenant storm survived — 0 dropped "
          "accepted requests on both legs, abusive tenant isolated, "
          "per-class p99 ordering held, full autoscale cycle + rolling "
          "update under fire, census unchanged")
    return 0


def lint_mode(args):
    """Incremental-analyzer smoke: cold run, warm run, compare (ISSUE 5).

    Both runs cover the full gate surface (mxnet_tpu + tools +
    bench.py) with ALL findings serialized — suppressed ones included —
    so the byte-comparison covers the suppression/justification channel,
    not just the live-findings one.
    """
    import json
    import shutil

    from tools.analysis import analyze, to_sarif

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache_dir = tempfile.mkdtemp(prefix="chaos_lint_cache_")
    paths = [os.path.join(root, "mxnet_tpu"),
             os.path.join(root, "tools"),
             os.path.join(root, "bench.py")]
    try:
        t0 = time.perf_counter()
        cold = analyze(paths, root=root, use_cache=True,
                       cache_dir=cache_dir)
        cold_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        warm = analyze(paths, root=root, use_cache=True,
                       cache_dir=cache_dir)
        warm_s = time.perf_counter() - t0
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    cold_json = json.dumps([f.to_dict() for f in cold], sort_keys=True)
    warm_json = json.dumps([f.to_dict() for f in warm], sort_keys=True)
    speedup = cold_s / warm_s if warm_s > 0 else float("inf")
    print(f"[chaos_check] lint: cold={cold_s:.2f}s warm={warm_s:.2f}s "
          f"speedup={speedup:.1f}x findings={len(cold)} "
          f"(live={sum(1 for f in cold if not f.suppressed)})")
    fails = []
    if cold_json != warm_json:
        fails.append("cached re-run changed the findings (byte mismatch)")
    if to_sarif(cold) != to_sarif(warm):
        fails.append("cached re-run changed the SARIF serialization")
    if speedup < 5.0:
        fails.append(f"cached re-run only {speedup:.1f}x faster (< 5x): "
                     f"the cache is not actually short-circuiting")
    if cold_s > 30.0:
        fails.append(f"cold full-tree run took {cold_s:.1f}s (> 30s "
                     f"budget)")
    if warm_s > 5.0:
        fails.append(f"warm run took {warm_s:.1f}s (> 5s budget)")
    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: warm run {speedup:.1f}x faster, "
          f"byte-identical findings")
    return 0


def cost_mode(args):
    """Cold-vs-warm budget audit over every committed budget (ISSUE 6).

    The costguard report cache is keyed by a hash of the LOWERED HLO
    text, so the warm run still builds and lowers every entry point
    (that work is what proves the cache key matches the code) but must
    skip every XLA compile.  A cache that changes a verdict — or that
    does not actually shortcut the compiles — fails here.
    """
    import shutil

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    from tools import costguard

    cache_dir = tempfile.mkdtemp(prefix="chaos_cost_cache_")
    try:
        t0 = time.perf_counter()
        cold = costguard.run_check(root=root, use_cache=True,
                                   cache_dir=cache_dir)
        cold_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        warm = costguard.run_check(root=root, use_cache=True,
                                   cache_dir=cache_dir)
        warm_s = time.perf_counter() - t0
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    speedup = cold_s / warm_s if warm_s > 0 else float("inf")
    n = len(cold.entries)
    sharded = [e for e in cold.entries
               if (e.report.get("per_device") or {}).get("n_devices",
                                                         1) > 1]
    print(f"[chaos_check] cost: cold={cold_s:.2f}s warm={warm_s:.2f}s "
          f"speedup={speedup:.1f}x entries={n} "
          f"(sharded={len(sharded)}) "
          f"executables={sum(e.report['n_executables'] for e in cold.entries)}")
    fails = []
    if not cold.ok:
        fails.append("cold budget audit FAILED:\n" + cold.render())
    if not warm.ok:
        fails.append("warm budget audit FAILED:\n" + warm.render())
    if cold.to_json() != warm.to_json():
        fails.append("cached re-run changed the audit verdicts "
                     "(byte mismatch)")
    # ISSUE 11: the cold-vs-warm byte-identity must cover SHARDED
    # goldens too — per-device numbers ride the same report cache, and
    # a cache that dropped (or fabricated) a per_device section would
    # silently un-gate the ∝ 1/shards contracts
    if not sharded:
        fails.append("no sharded entry (per_device.n_devices > 1) in "
                     "the audited set — the per-device budget surface "
                     "is not covered")
    for e in sharded:
        pd = e.report["per_device"]
        if not (pd.get("argument_bytes", 0) > 0
                and pd.get("peak_bytes", 0) > 0):
            fails.append(f"sharded entry {e.name}: per_device bytes "
                         f"missing/zero ({pd}) — extraction went dark")
    if speedup < 1.5:
        fails.append(f"cached re-run only {speedup:.1f}x faster (< 1.5x): "
                     f"the report cache is not skipping compiles "
                     f"(lower/build still run warm — by design — so the "
                     f"bar is lower than lint's)")
    if cold_s > 150.0:
        fails.append(f"cold full audit took {cold_s:.1f}s (> 150s budget)")
    if warm_s > 75.0:
        fails.append(f"warm audit took {warm_s:.1f}s (> 75s budget)")
    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: warm audit {speedup:.1f}x faster, "
          f"byte-identical verdicts, all {n} budgets green")
    return 0


def hlo_mode(args):
    """Cold-vs-warm structural-lint audit over every hloguard surface
    (ISSUE 18).

    Lowering every surface is deterministic and paid ONCE up front
    (``surfaces.build`` memoizes per process) so the cold/warm timings
    isolate exactly what the ``.hloguard_cache`` shortcuts: the
    parse/extract stage keyed by the lowered-text hash.  The warm run
    must come back byte-identical in verdicts — findings, suppressions
    and censuses included — and actually skip the parse.
    """
    import shutil

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    from tools import hloguard
    from tools.hloguard import surfaces as hlo_surfaces

    t0 = time.perf_counter()
    names = hlo_surfaces.names()
    n_programs = sum(len(hlo_surfaces.build(n).programs) for n in names)
    build_s = time.perf_counter() - t0

    cache_dir = tempfile.mkdtemp(prefix="chaos_hlo_cache_")
    try:
        t0 = time.perf_counter()
        cold = hloguard.run_check(root=root, use_cache=True,
                                  cache_dir=cache_dir)
        cold_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        warm = hloguard.run_check(root=root, use_cache=True,
                                  cache_dir=cache_dir)
        warm_s = time.perf_counter() - t0
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    speedup = cold_s / warm_s if warm_s > 0 else float("inf")
    n_sup = sum(1 for f in cold.findings if f.suppressed)
    print(f"[chaos_check] hlo: build={build_s:.2f}s cold={cold_s:.2f}s "
          f"warm={warm_s:.2f}s speedup={speedup:.1f}x "
          f"surfaces={len(cold.entries)} programs={n_programs} "
          f"(suppressed={n_sup})")
    fails = []
    if not cold.ok:
        fails.append("cold structural audit FAILED:\n" + cold.render())
    if not warm.ok:
        fails.append("warm structural audit FAILED:\n" + warm.render())
    if cold.to_json() != warm.to_json():
        fails.append("cached re-run changed the audit verdicts "
                     "(byte mismatch)")
    ungated = [e.name for e in cold.entries if not e.gated]
    if ungated:
        fails.append(f"surfaces not gated (golden/env mismatch): "
                     f"{ungated} — the audit went dark on them")
    if speedup < 5.0:
        fails.append(f"cached re-run only {speedup:.1f}x faster (< 5x): "
                     f"the facts cache is not skipping the parse "
                     f"(lowering is prebuilt, so parse/extract is all "
                     f"the cold run pays)")
    if cold_s > 60.0:
        fails.append(f"cold parse/extract audit took {cold_s:.1f}s "
                     f"(> 60s budget)")
    if warm_s > 10.0:
        fails.append(f"warm audit took {warm_s:.1f}s (> 10s budget)")
    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: warm audit {speedup:.1f}x faster, "
          f"byte-identical verdicts, all {len(cold.entries)} surfaces "
          f"structurally green")
    return 0


def elastic_mode(args):
    """Supervised-gang chaos (ISSUE 9): SIGKILL + SIGSTOP-hang +
    supervisor-SIGTERM legs over a real 2-worker CPU training gang."""
    import json
    import signal
    import threading

    from mxnet_tpu import elastic

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "elastic_worker.py")
    fails = []

    def wait_for(pred, timeout, what):
        t0 = time.time()
        while time.time() - t0 < timeout:
            v = pred()
            if v:
                return v
            time.sleep(0.05)
        fails.append(f"timed out after {timeout}s waiting for {what}")
        return None

    def spawn_pids(sup, attempt):
        for rec in sup.log.records:
            if rec["event"] == "spawn" and rec["attempt"] == attempt:
                return rec["pids"]
        return None

    def hb_step(sup, rank, attempt):
        rec = elastic.read_heartbeats(sup.heartbeat_dir).get(rank)
        if rec and int(rec.get("attempt", -1)) == attempt:
            return int(rec["global_step"])
        return 0

    def assert_reaped(sup):
        pids = {p for r in sup.log.records if r["event"] == "spawn"
                for p in r["pids"]}
        for pid in sorted(pids):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                continue
            fails.append(f"worker pid {pid} leaked past supervisor exit")

    def build(td, target, max_restarts, env):
        return elastic.Supervisor(
            [sys.executable, worker], 2, platform="cpu",
            devices_per_worker=1, max_restarts=max_restarts,
            watchdog_secs=5.0, startup_grace_secs=180.0,
            graceful_secs=30.0, backoff_base=0.2,
            heartbeat_dir=os.path.join(td, "hb"),
            event_log=os.path.join(td, "events.jsonl"),
            progress_dir=os.path.join(td, "ckpt"),
            extra_env=dict(env, MXTPU_TARGET_STEP=str(target),
                           MXTPU_CKPT_DIR=os.path.join(td, "ckpt"),
                           PYTHONPATH=root + os.pathsep +
                           os.environ.get("PYTHONPATH", "")))

    # ---- leg A: SIGKILL one worker mid-epoch, SIGSTOP the other ----------
    target = 14
    td = tempfile.mkdtemp(prefix="chaos_elastic_")
    sup = build(td, target, max_restarts=2,
                env={"MXTPU_STEP_SLEEP": "0.15", "MXTPU_ROUNDTRIP": "1"})
    stopped = []

    def chaos_script():
        # SIGKILL rank 1 once attempt 0 committed real progress
        if wait_for(lambda: hb_step(sup, 1, 0) >= 5, 300,
                    "attempt 0 rank 1 to pass step 5") is None:
            sup.request_stop()
            return
        os.kill(spawn_pids(sup, 0)[1], signal.SIGKILL)
        print("[chaos_check] elastic: SIGKILLed rank 1 mid-epoch",
              flush=True)
        # SIGSTOP rank 0 of attempt 1 once it advanced further
        if wait_for(lambda: hb_step(sup, 0, 1) >= 9, 300,
                    "attempt 1 rank 0 to pass step 9") is None:
            sup.request_stop()
            return
        pid = spawn_pids(sup, 1)[0]
        os.kill(pid, signal.SIGSTOP)
        stopped.append(pid)
        print("[chaos_check] elastic: SIGSTOPed rank 0 (watchdog bait)",
              flush=True)

    t = threading.Thread(target=chaos_script)
    t.start()
    try:
        rc = sup.run()
    finally:
        t.join()
        for pid in stopped:        # belt+braces: never leave one stopped
            try:
                os.kill(pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
    evs = [r["event"] for r in sup.log.records]
    final = elastic.latest_committed_step(sup.progress_dir)
    restarts = evs.count("restart")
    starts = [r["progress"] for r in sup.log.records
              if r["event"] == "spawn"]
    print(f"[chaos_check] elastic: rc={rc} final_step={final} "
          f"restarts={restarts} spawn_progress={starts} events={evs}")
    if rc != 0:
        fails.append(f"leg A: supervisor exited rc={rc}, wanted 0")
    if final is None or final < target:
        fails.append(f"leg A: committed step {final} < target {target}")
    if restarts != 2:
        fails.append(f"leg A: expected exactly 2 restarts "
                     f"(SIGKILL + watchdog), saw {restarts}")
    if "heartbeat-stale" not in evs:
        fails.append("leg A: the SIGSTOP never tripped the watchdog")
    if "giveup" in evs:
        fails.append("leg A: supervisor gave up inside budget")
    resumes = [s for s in starts[1:]]
    if any(s in (None, 0) for s in resumes):
        fails.append(f"leg A: a restart resumed from step 0: {starts}")
    if resumes != sorted(resumes) or len(set(resumes)) != len(resumes):
        fails.append(f"leg A: per-attempt resume steps not strictly "
                     f"increasing: {starts}")
    with open(sup.event_log) as f:
        for line in f:
            json.loads(line)       # every event line is valid JSON
    assert_reaped(sup)

    # ---- leg B: SIGTERM the supervisor itself ----------------------------
    td2 = tempfile.mkdtemp(prefix="chaos_elastic_term_")
    sup2 = build(td2, target=10_000, max_restarts=1,
                 env={"MXTPU_STEP_SLEEP": "0.15"})

    def term_script():
        if wait_for(lambda: hb_step(sup2, 0, 0) >= 4 and
                    hb_step(sup2, 1, 0) >= 4, 300,
                    "leg B workers to pass step 4") is None:
            sup2.request_stop()
            return
        os.kill(os.getpid(), signal.SIGTERM)
        print("[chaos_check] elastic: SIGTERMed the supervisor",
              flush=True)

    t2 = threading.Thread(target=term_script)
    t2.start()
    try:
        rc2 = sup2.run()
    finally:
        t2.join()
    evs2 = [r["event"] for r in sup2.log.records]
    statuses = [r["status"] for r in sup2.log.records
                if r["event"] == "worker-exit"]
    final2 = elastic.latest_committed_step(sup2.progress_dir)
    print(f"[chaos_check] elastic: SIGTERM leg rc={rc2} "
          f"statuses={statuses} snapshot_step={final2} events={evs2}")
    if rc2 != 0:
        fails.append(f"leg B: supervisor SIGTERM exit rc={rc2}, wanted 0")
    if "preempted" not in evs2 or "forward-sigterm" not in evs2:
        fails.append(f"leg B: missing forward-sigterm/preempted events: "
                     f"{evs2}")
    if statuses != ["preempted", "preempted"]:
        fails.append(f"leg B: workers did not snapshot-then-exit: "
                     f"{statuses}")
    if not final2:
        fails.append("leg B: no snapshot committed before exit")
    assert_reaped(sup2)

    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: SIGKILL + SIGSTOP-hang recovered within "
          f"budget ({restarts} restarts, resumes {resumes}, reached step "
          f"{final}); supervisor SIGTERM drained to {statuses} with "
          f"snapshot at step {final2}; 0 leaked workers")
    return 0


_CKPT_WORKER = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import jax
import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel.checkpoint import CheckpointManager

mx.random.seed(7)
net = nn.HybridSequential()
net.add(nn.Dense(16, activation="relu", in_units=8),
        nn.Dense(4, in_units=16))
net.initialize()
mesh = parallel.make_mesh(dp=len(jax.devices()))
step = parallel.TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          mx.optimizer.create("adam"), mesh=mesh)
rng = np.random.RandomState(0)
x, y = rng.randn(16, 8).astype(np.float32), rng.randint(0, 4, (16,))
step(x, y)
mgr = CheckpointManager(step, sys.argv[1], every_n_steps=1, keep_last=4)
mgr.resume_latest()
while True:                     # snapshot storm until SIGKILLed
    step(x, y)
    mgr.maybe_save()
"""


def ckpt_mode(args):
    """Durable-checkpoint chaos (ISSUE 17): kill -9 mid-write storm +
    fault-armed bit-flip corruption + retention pruning under a live
    WeightUpdater.  Resume must always land on an intact digest-verified
    snapshot; corrupted bytes must never be trained on or served."""
    import signal
    import subprocess
    import tempfile as _tempfile

    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import fault, gluon, parallel, serving
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.checkpoint import (BitFlipInjection,
                                               CheckpointCorruptError,
                                               CheckpointManager,
                                               list_checkpoints,
                                               load_snapshot_params,
                                               resume_latest,
                                               verify_checkpoint)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fails = []

    def step_for(seed):
        mx.random.seed(seed)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
        net.initialize()
        mesh = parallel.make_mesh(dp=len(jax.devices()))
        return parallel.TrainStep(net,
                                  gluon.loss.SoftmaxCrossEntropyLoss(),
                                  mx.optimizer.create("adam"), mesh=mesh)

    rng = np.random.RandomState(0)
    x, y = rng.randn(16, 8).astype(np.float32), rng.randint(0, 4, (16,))
    survivor = step_for(99)
    survivor(x, y)                       # build once, reused every leg

    # ---- leg A: kill -9 a snapshot storm, repeatedly ---------------------
    d = _tempfile.mkdtemp(prefix="chaos_ckpt_")
    env = dict(os.environ, PYTHONPATH=root + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    kills = 3
    def newest(directory):
        cks = list_checkpoints(directory)
        return cks[-1][0] if cks else 0

    for round_no in range(kills):
        # retention caps the COUNT at keep_last, so progress is measured
        # by the newest committed num_update, not directory size
        before = newest(d)
        proc = subprocess.Popen([sys.executable, "-c", _CKPT_WORKER, d],
                                env=env)
        t0 = time.time()
        while newest(d) < before + 2 and \
                time.time() - t0 < 120 and proc.poll() is None:
            time.sleep(0.02)
        if proc.poll() is not None:
            fails.append(f"leg A round {round_no}: worker exited "
                         f"rc={proc.returncode} before the kill")
            break
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        cks = list_checkpoints(d)
        if newest(d) < before + 2:
            fails.append(f"leg A round {round_no}: storm advanced the "
                         f"newest snapshot from {before} to {newest(d)}, "
                         f"wanted >= {before + 2}")
        for _, path in cks:              # every COMMITTED name verifies —
            try:                         # atomic commit + fsync means a
                verify_checkpoint(path)  # kill can never corrupt one
            except Exception as exc:     # noqa: BLE001
                fails.append(f"leg A round {round_no}: committed snapshot "
                             f"{os.path.basename(path)} failed "
                             f"verification after kill -9: {exc}")
        n = resume_latest(survivor, d)
        if n is None:
            fails.append(f"leg A round {round_no}: resume found nothing")
        print(f"[chaos_check] ckpt: kill round {round_no}: "
              f"{len(cks)} committed, all verified, resumed at step {n}",
              flush=True)

    # ---- leg B: fault-armed bit-flip — damage, never poison --------------
    d2 = _tempfile.mkdtemp(prefix="chaos_ckpt_flip_")
    victim = step_for(7)
    victim(x, y)
    mgr2 = CheckpointManager(victim, d2, keep_last=10)
    mgr2.save()                          # intact
    good = int(victim._num_update)
    victim(x, y)
    with fault.inject("checkpoint.serialize", BitFlipInjection(), times=1):
        corrupt_path = mgr2.save()       # committed but digest-poisoned
    try:
        verify_checkpoint(corrupt_path)
        fails.append("leg B: verify_checkpoint passed a bit-flipped "
                     "snapshot")
    except CheckpointCorruptError:
        pass
    try:
        load_snapshot_params(corrupt_path)
        fails.append("leg B: load_snapshot_params served corrupted bytes")
    except CheckpointCorruptError:
        pass
    n = resume_latest(survivor, d2)
    if n != good:
        fails.append(f"leg B: resume landed on step {n}, wanted the "
                     f"older intact snapshot {good}")
    print(f"[chaos_check] ckpt: bit-flip rejected everywhere, resume "
          f"fell back to intact step {good}", flush=True)

    # ---- leg C: prune race + corrupt stream under a live updater ---------
    d3 = _tempfile.mkdtemp(prefix="chaos_ckpt_race_")
    trainer = step_for(7)
    trainer(x, y)
    # keep_last=1: retention prunes everything but the newest — the
    # tightest possible race against the polling reader
    mgr3 = CheckpointManager(trainer, d3, keep_last=1)
    mgr3.save()
    params, _ = load_snapshot_params(mgr3.checkpoints()[-1][1])
    shapes = [tuple(p.shape) for p in params]
    iw1, ib1 = shapes.index((16, 8)), shapes.index((16,))
    iw2, ib2 = shapes.index((4, 16)), shapes.index((4,))

    @jax.jit
    def fwd(p, xx):
        h = jnp.maximum(xx @ p[iw1].T + p[ib1], 0.0)
        return h @ p[iw2].T + p[ib2]

    applies = [serving.HotSwapApply(
        lambda p, xx: np.asarray(fwd(p, xx)), list(params))
        for _ in range(2)]
    fleet = serving.ServingFleet(applies, buckets=(1, 4), max_delay=0.002,
                                 sample=np.ones((8,), np.float32),
                                 name="ChaosCkptFleet")
    fleet.start()
    updater = serving.WeightUpdater(fleet, mgr3, poll=0.01)
    updater.start()
    corrupt_round = 3
    try:
        for round_no in range(1, 6):
            trainer(x, y)
            if round_no == corrupt_round:
                with fault.inject("checkpoint.serialize",
                                  BitFlipInjection(), times=1):
                    mgr3.save()
                t0 = time.time()
                while updater.skipped < 1 and time.time() - t0 < 30:
                    time.sleep(0.01)
                if updater.skipped < 1:
                    fails.append("leg C: the corrupt snapshot was never "
                                 "rejected by the updater")
            else:
                want_applied = updater.applied + 1
                mgr3.save()
                t0 = time.time()
                while updater.applied < want_applied and \
                        time.time() - t0 < 30:
                    time.sleep(0.01)
                if updater.applied < want_applied:
                    fails.append(f"leg C: rolling update {round_no} "
                                 f"dropped (applied={updater.applied}, "
                                 f"skipped={updater.skipped})")
        # deterministic prune-vs-reader race: the path vanishes between
        # discovery and read — stale (re-poll), never a bad snapshot
        pruned = os.path.join(d3, "ckpt-99999999.npz")
        final = mgr3.checkpoints()[-1][1]
        import shutil
        shutil.copy(final, pruned)
        os.remove(pruned)
        skipped_before = updater.skipped
        try:
            updater.update(pruned)
            fails.append("leg C: updating a pruned path did not raise")
        except serving.SnapshotPrunedError:
            pass
        except Exception as exc:        # noqa: BLE001
            fails.append(f"leg C: pruned path raised {type(exc).__name__}"
                         f" instead of SnapshotPrunedError: {exc}")
        if updater.skipped != skipped_before:
            fails.append("leg C: a pruned (stale) path was counted as a "
                         "skipped snapshot")
    finally:
        updater.stop(timeout=10)
        fleet.drain(timeout=10)
    # the fleet must serve the FINAL committed snapshot's weights — the
    # corrupt round's bytes must never have reached a replica
    want = np.asarray(fwd(
        [jnp.asarray(p) for p in
         load_snapshot_params(mgr3.checkpoints()[-1][1])[0]],
        np.ones((1, 8), np.float32)))[0]
    got = np.asarray(applies[0](np.ones((1, 8), np.float32)))[0]
    if not np.allclose(got, want):
        fails.append("leg C: replica does not serve the final intact "
                     "snapshot's weights")
    print(f"[chaos_check] ckpt: race leg applied={updater.applied} "
          f"skipped={updater.skipped} (corrupt stream rejected, prune "
          f"race re-polled)", flush=True)

    if fails:
        for f in fails:
            print(f"[chaos_check] FAIL: {f}")
        return 1
    print(f"[chaos_check] PASS: {kills} kill -9 rounds left only "
          f"verified-intact committed snapshots; bit-flip rejected by "
          f"verify/load/resume; live updater under keep_last=1 pruning "
          f"applied {updater.applied} updates, rejected the corrupt "
          f"one, and served the final intact weights")
    return 0


MODES = {
    "train": ("kill-and-resume training smoke (ISSUE 2)", None),
    "serve": ("inject-and-drain serving smoke (ISSUE 4)", serve_mode),
    "fleet": ("replica-kill + rolling weight updates + SIGTERM "
              "(ISSUES 7/8)", fleet_mode),
    "llm": ("decode-fault burst + SIGTERM mid-decode on the "
            "continuous-batching LLM server (ISSUE 10)", llm_mode),
    "lint": ("incremental-analyzer cold-vs-warm contract (ISSUE 5)",
             lint_mode),
    "cost": ("cold-vs-warm compiled-cost budget audit (ISSUE 6)",
             cost_mode),
    "hlo": ("cold-vs-warm structural HLO lint audit over every "
            "hloguard surface (ISSUE 18)", hlo_mode),
    "elastic": ("supervised-gang SIGKILL + SIGSTOP-hang + supervisor "
                "SIGTERM (ISSUE 9)", elastic_mode),
    "slo": ("mixed-tenant QoS storm + replica kill + autoscale cycle + "
            "rolling update, plus disaggregated prefill/decode "
            "(ISSUE 12)", slo_mode),
    "obs": ("traced storm + replica kill + fault burst: complete span "
            "trees, attribution sums, off-switch overhead bound "
            "(ISSUE 13)", obs_mode),
    "ckpt": ("kill -9 mid-write storm + armed bit-flip corruption + "
             "retention-prune race under a live WeightUpdater "
             "(ISSUE 17)", ckpt_mode),
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=tuple(MODES), default="train",
                    help="train: kill-and-resume; serve: inject-and-"
                         "drain; fleet: replica-kill + rolling weight "
                         "updates + SIGTERM; lint: incremental analyzer "
                         "contract; cost: cold-vs-warm budget audit; "
                         "elastic: supervised-gang chaos")
    ap.add_argument("--list-modes", action="store_true",
                    help="print the mode registry and exit")
    ap.add_argument("--steps", type=int, default=8,
                    help="total training steps in the reference run")
    ap.add_argument("--every", type=int, default=2,
                    help="checkpoint cadence (steps)")
    ap.add_argument("--keep", type=int, default=2,
                    help="retention: keep-last-K snapshots")
    ap.add_argument("--crash-after", type=int, default=None,
                    help="crash on this step call (default: steps//2 + 1)")
    ap.add_argument("--requests", type=int, default=25,
                    help="serve mode: requests per client thread")
    args = ap.parse_args(argv)
    if args.list_modes:
        for name, (desc, _) in MODES.items():
            print(f"{name:<10} {desc}")
        return 0
    mode_fn = MODES[args.mode][1]
    if mode_fn is not None:
        return mode_fn(args)
    crash_after = (args.crash_after if args.crash_after is not None
                   else args.steps // 2 + 1)

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import fault, gluon, parallel
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.checkpoint import CheckpointManager, resume_latest

    def net(seed):
        mx.random.seed(seed)
        n = nn.HybridSequential()
        n.add(nn.Dense(16, activation="relu", in_units=8),
              nn.Dense(4, in_units=16))
        n.initialize()
        return n

    def step_for(seed):
        mesh = parallel.make_mesh(dp=len(jax.devices()))
        return parallel.TrainStep(net(seed),
                                  gluon.loss.SoftmaxCrossEntropyLoss(),
                                  mx.optimizer.create("adam"), mesh=mesh)

    rng = np.random.RandomState(0)
    batches = [(rng.randn(16, 8).astype(np.float32),
                rng.randint(0, 4, (16,))) for _ in range(args.steps)]

    print(f"[chaos_check] reference run: {args.steps} steps")
    ref = []
    ref_step = step_for(7)
    for x, y in batches:
        ref.append(float(ref_step(x, y).asnumpy()))

    d = tempfile.mkdtemp(prefix="chaos_check_")
    print(f"[chaos_check] victim run: checkpoints every {args.every} steps "
          f"to {d}, crash injected on step {crash_after}")
    victim = step_for(7)
    mgr = CheckpointManager(victim, d, every_n_steps=args.every,
                            keep_last=args.keep)
    crashed = False
    with fault.inject("step", RuntimeError("injected preemption"),
                      after_n=crash_after - 1):
        try:
            for x, y in batches:
                victim(x, y)
                mgr.maybe_save()
        except RuntimeError as exc:
            crashed = True
            print(f"[chaos_check] victim died as planned: {exc}")
    if not crashed:
        print("[chaos_check] FAIL: injected crash never fired")
        return 1
    del victim, mgr

    survivor = step_for(99)        # different init — checkpoint must win
    survivor(*batches[0])          # build/compile
    n = resume_latest(survivor, d)
    if n is None:
        print("[chaos_check] FAIL: resume_latest found no checkpoint")
        return 1
    print(f"[chaos_check] resumed from step {n}, replaying "
          f"{args.steps - n} steps")
    resumed = [float(survivor(x, y).asnumpy()) for x, y in batches[n:]]

    if resumed == ref[n:]:
        print(f"[chaos_check] PASS: resumed trajectory bit-exact over "
              f"{len(resumed)} steps")
        return 0
    diff = np.max(np.abs(np.array(resumed) - np.array(ref[n:])))
    print(f"[chaos_check] FAIL: trajectories diverge (max |diff|={diff})")
    print(f"  reference: {ref[n:]}")
    print(f"  resumed  : {resumed}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
