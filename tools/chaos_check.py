#!/usr/bin/env python
"""Chaos smoke: one kill-and-resume cycle on the CPU backend.

Runs a small training loop with periodic checkpoints, injects a crash
mid-run via ``fault.inject``, rediscovers the newest snapshot with
``resume_latest``, and checks the resumed loss trajectory matches an
uninterrupted run bit-exactly — the acceptance contract of ISSUE 2, as a
single command for CI and for eyeballing a fresh checkout::

    python tools/chaos_check.py [--steps 8] [--every 2] [--keep 2]

Exit code 0 on success, 1 on any mismatch.  Forces ``JAX_PLATFORMS=cpu``
(and an 8-device virtual mesh) so it runs anywhere, TPU or not.
"""
import argparse
import os
import sys
import tempfile

# must precede any jax import — same bring-up as tests/conftest.py
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=8,
                    help="total training steps in the reference run")
    ap.add_argument("--every", type=int, default=2,
                    help="checkpoint cadence (steps)")
    ap.add_argument("--keep", type=int, default=2,
                    help="retention: keep-last-K snapshots")
    ap.add_argument("--crash-after", type=int, default=None,
                    help="crash on this step call (default: steps//2 + 1)")
    args = ap.parse_args(argv)
    crash_after = (args.crash_after if args.crash_after is not None
                   else args.steps // 2 + 1)

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import fault, gluon, parallel
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.checkpoint import CheckpointManager, resume_latest

    def net(seed):
        mx.random.seed(seed)
        n = nn.HybridSequential()
        n.add(nn.Dense(16, activation="relu", in_units=8),
              nn.Dense(4, in_units=16))
        n.initialize()
        return n

    def step_for(seed):
        mesh = parallel.make_mesh(dp=len(jax.devices()))
        return parallel.TrainStep(net(seed),
                                  gluon.loss.SoftmaxCrossEntropyLoss(),
                                  mx.optimizer.create("adam"), mesh=mesh)

    rng = np.random.RandomState(0)
    batches = [(rng.randn(16, 8).astype(np.float32),
                rng.randint(0, 4, (16,))) for _ in range(args.steps)]

    print(f"[chaos_check] reference run: {args.steps} steps")
    ref = []
    ref_step = step_for(7)
    for x, y in batches:
        ref.append(float(ref_step(x, y).asnumpy()))

    d = tempfile.mkdtemp(prefix="chaos_check_")
    print(f"[chaos_check] victim run: checkpoints every {args.every} steps "
          f"to {d}, crash injected on step {crash_after}")
    victim = step_for(7)
    mgr = CheckpointManager(victim, d, every_n_steps=args.every,
                            keep_last=args.keep)
    crashed = False
    with fault.inject("step", RuntimeError("injected preemption"),
                      after_n=crash_after - 1):
        try:
            for x, y in batches:
                victim(x, y)
                mgr.maybe_save()
        except RuntimeError as exc:
            crashed = True
            print(f"[chaos_check] victim died as planned: {exc}")
    if not crashed:
        print("[chaos_check] FAIL: injected crash never fired")
        return 1
    del victim, mgr

    survivor = step_for(99)        # different init — checkpoint must win
    survivor(*batches[0])          # build/compile
    n = resume_latest(survivor, d)
    if n is None:
        print("[chaos_check] FAIL: resume_latest found no checkpoint")
        return 1
    print(f"[chaos_check] resumed from step {n}, replaying "
          f"{args.steps - n} steps")
    resumed = [float(survivor(x, y).asnumpy()) for x, y in batches[n:]]

    if resumed == ref[n:]:
        print(f"[chaos_check] PASS: resumed trajectory bit-exact over "
              f"{len(resumed)} steps")
        return 0
    diff = np.max(np.abs(np.array(resumed) - np.array(ref[n:])))
    print(f"[chaos_check] FAIL: trajectories diverge (max |diff|={diff})")
    print(f"  reference: {ref[n:]}")
    print(f"  resumed  : {resumed}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
