"""Project tooling (im2rec, launch, chaos_check, and the ``analysis``
static-analysis suite — ``python -m tools.analysis mxnet_tpu/``)."""
