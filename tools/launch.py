#!/usr/bin/env python
"""Multi-process launcher.

ref: tools/launch.py + the dmlc-core tracker's local launcher
(3rdparty/dmlc-core/tracker/dmlc_tracker/local.py): export the DMLC_* env
contract, exec the user command N times, propagate failures.  TPU-native
differences: there are no server/scheduler roles (every process is a worker
talking to the jax.distributed coordination service — SURVEY.md §5.8), and
``--platform cpu`` rehearses a cluster on one machine with virtual devices
(SURVEY.md §4 "distributed-without-a-cluster").

    python tools/launch.py -n 4 python train.py ...
    python tools/launch.py -n 2 --platform cpu --devices-per-worker 2 \
        python tests/dist_worker.py
"""
import argparse
import os
import socket
import subprocess
import sys
import time


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--launcher", choices=["local"], default="local",
                   help="only the local launcher is built in; multi-host "
                        "bring-up passes explicit DMLC_* env instead")
    p.add_argument("--platform", default=None,
                   help="force JAX_PLATFORMS in workers (e.g. cpu for the "
                        "localhost rehearsal)")
    p.add_argument("--devices-per-worker", type=int, default=0,
                   help="with --platform cpu: virtual CPU devices per worker")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic mode: if any worker dies, tear the job "
                        "down and relaunch the whole gang up to N times "
                        "(pair with TrainStep checkpoints to resume; the "
                        "reference has no equivalent — SURVEY §5.3 names "
                        "failure recovery as a gap to exceed)")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")

    attempt = 0
    while True:
        rc = _run_gang(args, attempt)
        if rc == 0 or attempt >= args.max_restarts:
            return rc
        attempt += 1
        print(f"[launch] job failed (rc={rc}); restart "
              f"{attempt}/{args.max_restarts}", file=sys.stderr)


def _run_gang(args, attempt):
    """One gang launch: all workers, fresh coordinator port; kill the gang
    when any worker dies (partial gangs deadlock in collectives)."""
    port = _free_port()
    procs = []
    for i in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_WORKER_ID": str(i),
            "DMLC_ATTEMPT": str(attempt),
        })
        if args.platform:
            env["JAX_PLATFORMS"] = args.platform
            if args.platform == "cpu":
                # keep the axon/TPU plugin out of CPU rehearsal workers:
                # sitecustomize registers it at interpreter startup
                env.pop("PALLAS_AXON_POOL_IPS", None)
        if args.devices_per_worker:
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.devices_per_worker}").strip()
        procs.append(subprocess.Popen(args.command, env=env))

    rc = 0
    alive = set(range(len(procs)))
    while alive and rc == 0:
        for i in sorted(alive):
            r = procs[i].poll()
            if r is None:
                continue
            alive.discard(i)
            if r != 0:
                print(f"worker {i} exited with {r}", file=sys.stderr)
                rc = r
                break
        else:
            time.sleep(0.05)
    if rc:
        # fail-fast gang teardown (a dead peer hangs the others' collectives)
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    return rc


if __name__ == "__main__":
    sys.exit(main())
