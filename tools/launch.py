#!/usr/bin/env python
"""Elastic multi-process launcher.

ref: tools/launch.py + the dmlc-core tracker's local launcher
(3rdparty/dmlc-core/tracker/dmlc_tracker/local.py): export the DMLC_* env
contract, exec the user command N times, propagate failures.  TPU-native
differences: there are no server/scheduler roles (every process is a worker
talking to the jax.distributed coordination service — SURVEY.md §5.8), and
``--platform cpu`` rehearses a cluster on one machine with virtual devices
(SURVEY.md §4 "distributed-without-a-cluster").

Since ISSUE 9 this is a thin CLI over ``mxnet_tpu.elastic.Supervisor``:
per-rank heartbeats + a hang watchdog (``--watchdog-secs``), fail-fast
gang teardown with a snapshot-friendly SIGTERM→SIGKILL escalation,
progress-aware restarts (``--max-restarts`` refills whenever an attempt
advanced the committed checkpoint step under ``--progress-dir``), a JSONL
event log (``--event-log``), and ``[r<rank>]``-prefixed worker output
(or per-rank files under ``--log-dir``).

    python tools/launch.py -n 4 python train.py ...
    python tools/launch.py -n 2 --platform cpu --devices-per-worker 2 \
        python tests/dist_worker.py
    python tools/launch.py -n 2 --platform cpu --watchdog-secs 60 \
        --max-restarts 3 --progress-dir /ckpts --event-log events.jsonl \
        python train.py ...
"""
import argparse
import importlib.util
import os
import sys


def _load_elastic():
    """Load mxnet_tpu/elastic.py WITHOUT importing the package: the
    supervisor process must stay jax-free (the package import would pull
    the backend into the launcher — on a TPU host that can wedge device
    ownership away from the very workers it launches)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "mxnet_tpu", "elastic.py")
    spec = importlib.util.spec_from_file_location("_mxtpu_elastic", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--launcher", choices=["local"], default="local",
                   help="only the local launcher is built in; multi-host "
                        "bring-up passes explicit DMLC_* env instead")
    p.add_argument("--platform", default=None,
                   help="force JAX_PLATFORMS in workers (e.g. cpu for the "
                        "localhost rehearsal)")
    p.add_argument("--devices-per-worker", type=int, default=0,
                   help="with --platform cpu: virtual CPU devices per worker")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic mode: on any failure tear the gang down "
                        "and relaunch up to N times; with --progress-dir "
                        "the budget REFILLS whenever an attempt advanced "
                        "the committed checkpoint step, so long jobs "
                        "survive many spread-out faults while a pinned "
                        "crash-loop exhausts it fast (SURVEY §5.3 names "
                        "failure recovery as the gap to exceed)")
    p.add_argument("--watchdog-secs", type=float, default=0.0,
                   help="declare a worker hung when its heartbeat goes "
                        "stale this long (0 = no watchdog); workers "
                        "stamp heartbeats via Module.fit / "
                        "TrainStep(heartbeat=...) under the exported "
                        "MXTPU_HEARTBEAT_DIR contract")
    p.add_argument("--startup-grace-secs", type=float, default=None,
                   help="also declare a hang when a worker produced NO "
                        "heartbeat this long after spawn (covers a wedge "
                        "during bring-up, before step 1 exists); default "
                        "with a watchdog armed: 10x --watchdog-secs, "
                        "min 60s")
    p.add_argument("--graceful-secs", type=float, default=10.0,
                   help="SIGTERM→SIGKILL escalation window on teardown "
                        "(size it to cover one step + one snapshot)")
    p.add_argument("--backoff-base", type=float, default=0.5,
                   help="base delay of the exponential restart backoff")
    p.add_argument("--heartbeat-dir", default=None,
                   help="where workers stamp heartbeats (default: a "
                        "fresh temp dir, exported as MXTPU_HEARTBEAT_DIR)")
    p.add_argument("--progress-dir", default=None,
                   help="CheckpointManager directory to read committed "
                        "progress from (enables the budget refill and "
                        "per-attempt progress in the event log)")
    p.add_argument("--progress-prefix", default="ckpt",
                   help="checkpoint filename prefix under --progress-dir")
    p.add_argument("--log-dir", default=None,
                   help="tee each worker's output to r<rank>.log here "
                        "instead of prefixing the supervisor's streams")
    p.add_argument("--event-log", default=None,
                   help="append supervision events (spawn/heartbeat-stale/"
                        "teardown/restart/giveup) as JSONL here")
    p.add_argument("--no-prefix", action="store_true",
                   help="pass worker output through untagged (the "
                        "pre-ISSUE-9 behavior)")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")

    elastic = _load_elastic()
    sup = elastic.Supervisor(
        args.command, args.num_workers,
        platform=args.platform,
        devices_per_worker=args.devices_per_worker,
        max_restarts=args.max_restarts,
        watchdog_secs=args.watchdog_secs,
        startup_grace_secs=args.startup_grace_secs,
        graceful_secs=args.graceful_secs,
        backoff_base=args.backoff_base,
        heartbeat_dir=args.heartbeat_dir,
        log_dir=args.log_dir,
        event_log=args.event_log,
        progress_dir=args.progress_dir,
        progress_prefix=args.progress_prefix,
        prefix_output=not args.no_prefix)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
