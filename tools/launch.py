#!/usr/bin/env python
"""Multi-process launcher.

ref: tools/launch.py + the dmlc-core tracker's local launcher
(3rdparty/dmlc-core/tracker/dmlc_tracker/local.py): export the DMLC_* env
contract, exec the user command N times, propagate failures.  TPU-native
differences: there are no server/scheduler roles (every process is a worker
talking to the jax.distributed coordination service — SURVEY.md §5.8), and
``--platform cpu`` rehearses a cluster on one machine with virtual devices
(SURVEY.md §4 "distributed-without-a-cluster").

    python tools/launch.py -n 4 python train.py ...
    python tools/launch.py -n 2 --platform cpu --devices-per-worker 2 \
        python tests/dist_worker.py
"""
import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--launcher", choices=["local"], default="local",
                   help="only the local launcher is built in; multi-host "
                        "bring-up passes explicit DMLC_* env instead")
    p.add_argument("--platform", default=None,
                   help="force JAX_PLATFORMS in workers (e.g. cpu for the "
                        "localhost rehearsal)")
    p.add_argument("--devices-per-worker", type=int, default=0,
                   help="with --platform cpu: virtual CPU devices per worker")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")

    port = _free_port()
    procs = []
    for i in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_WORKER_ID": str(i),
        })
        if args.platform:
            env["JAX_PLATFORMS"] = args.platform
            if args.platform == "cpu":
                # keep the axon/TPU plugin out of CPU rehearsal workers:
                # sitecustomize registers it at interpreter startup
                env.pop("PALLAS_AXON_POOL_IPS", None)
        if args.devices_per_worker:
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.devices_per_worker}").strip()
        procs.append(subprocess.Popen(args.command, env=env))

    rc = 0
    for i, proc in enumerate(procs):
        r = proc.wait()
        if r != 0:
            print(f"worker {i} exited with {r}", file=sys.stderr)
            rc = rc or r
    if rc:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
    return rc


if __name__ == "__main__":
    sys.exit(main())
