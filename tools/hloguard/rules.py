"""hloguard rules: structural facts → findings + per-entry census.

Two enforcement layers, deliberately redundant (docs/analysis.md
"Structural HLO lint"):

1. **Pattern findings** — donation-gap, precision-leak,
   collective-schedule — are per-site diagnostics with the mxlint
   clean-tree discipline: fix it, or suppress it in the entry's golden
   with a written justification.
2. **Census pins** — every rule also contributes exact counts to the
   entry's structural census, diffed leaf-for-leaf against the
   committed golden.  A suppressed pattern can therefore never silently
   absorb NEW regressions: the counts move, the census trips.

Facts extraction is pure text → JSON (cacheable under the HLO-hash
FileCache); rule evaluation over facts is cheap and always runs.
"""
from __future__ import annotations

import hashlib

from . import hlo

#: bump when facts extraction or any rule's logic changes — keys the
#: .hloguard_cache signature AND is recorded in structural goldens, so
#: neither a stale cached record nor an old-schema golden can pass
REPORT_VERSION = "1.0"

#: a parameter smaller than this never raises donation-gap — tiny
#: scalars/counters are not worth donation plumbing (64 KiB)
DONATION_BYTES_FLOOR = 1 << 16

_FLOAT = {"f32", "f64", "bf16", "f16"}
#: "quantized" dtypes for the laundering chain rule: a convert UP from
#: one of these to f32 reaching a convert DOWN back is the pattern that
#: silently forfeits the int8 win (EQuARX, arXiv:2506.17615)
_QUANT = {"i8", "i4", "s8", "u8", "s4", "u4", "f8e4m3fn", "f8e5m2"}
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "i8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2, "i16": 2,
    "s32": 4, "u32": 4, "f32": 4, "i32": 4,
    "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "f64": 8, "i64": 8, "c64": 8, "c128": 16,
}

RULES = {
    "donation-gap": (
        "large float ENTRY parameter matches an output shape/dtype but "
        "is not donated (input_output_alias / jax.buffer_donor)"),
    "precision-leak": (
        "f32 dot/conv in a bf16/int8-policy entry, or a convert up/down "
        "chain laundering quantized values through f32"),
    "collective-schedule": (
        "per-entry collective census by kind, collectives inside while "
        "bodies, all-reduce where the golden pins a two-phase exchange"),
    "copy-churn": (
        "copy/transpose instruction counts pinned per entry — layout "
        "regressions caught before they show up as bytes"),
    "custom-call-census": (
        "unique-vs-total Pallas/Mosaic custom-call payloads per entry "
        "(the static dedup metric for ROADMAP item 4)"),
    "hlo-structure": (
        "program count / parse health of the entry's lowered modules"),
    "missing-golden": (
        "registered surface has no committed structural golden under "
        "tests/goldens/hloguard/"),
    "stale-golden": (
        "committed structural golden whose surface is no longer "
        "registered"),
    "stale-suppression": (
        "golden suppression that matched no finding — delete it or fix "
        "its match string"),
    "bad-suppression": (
        "golden suppression without a written justification (cannot "
        "itself be suppressed)"),
}


def _short_hash(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:12]


def _nbytes(dims, dtype) -> int:
    unit = _DTYPE_BYTES.get(dtype or "", 0)
    n = unit
    for d in dims or ():
        n *= d
    return n


def extract_facts(text: str) -> dict:
    """Parse one lowered module and distil the JSON-safe facts every
    rule consumes.  This is the expensive half (memoized by the
    HLO-hash cache); rules over facts are cheap and always run."""
    mod = hlo.parse_module(text)
    if not mod.ok or mod.main is None:
        return {"ok": False,
                "error": mod.error or "no public entry function"}
    reach = hlo.reachable_funcs(mod)
    while_funcs = hlo.funcs_reached_from_while(mod)
    main = mod.main

    params = [{
        "index": p.index,
        "dtype": p.dtype,
        "dims": list(p.dims) if p.dims is not None else None,
        "bytes": _nbytes(p.dims, p.dtype),
        "aliased": p.aliased,
        "donor": p.donor,
    } for p in main.params]
    outputs = [{"dtype": dt, "dims": list(dims) if dims is not None
                else None} for dims, dt in main.results]

    f32_dot_conv = []
    launder = []
    coll_by_kind: dict = {}
    coll_in_while = 0
    copies = {"copy": 0, "transpose": 0}
    cc_targets: dict = {}
    pallas_payloads = []
    pallas_normalized = []

    def _is_up_convert(op):
        return (op.kind == "convert" and op.operand_types
                and op.result_types
                and op.operand_types[0][1] in _QUANT
                and op.result_types[0][1] in ("f32", "f64"))

    for fname in sorted(reach):
        func = mod.funcs[fname]
        in_while_func = fname in while_funcs
        for op in func.ops:
            if op.kind in ("dot_general", "dot", "convolution"):
                op_dts = [dt for _, dt in op.operand_types[:2]]
                if len(op_dts) >= 2 and all(dt == "f32" for dt in op_dts):
                    f32_dot_conv.append(
                        {"kind": op.kind, "func": fname, "line": op.line})
            elif op.kind == "convert":
                if (op.operand_types and op.result_types
                        and op.operand_types[0][1] in ("f32", "f64")
                        and op.result_types[0][1] in _QUANT):
                    # a dot/conv between the converts means the f32
                    # interlude IS the compute (the quantized-wire
                    # dequant->matmul->quant pattern, which is the
                    # point) — only a compute-free up/down round trip
                    # launders
                    up = hlo.trace_back(
                        func, op, _is_up_convert,
                        stop=lambda d: d.kind in ("dot_general", "dot",
                                                  "convolution"))
                    if up is not None:
                        launder.append({
                            "func": fname, "line": op.line,
                            "src": up.operand_types[0][1],
                            "dst": op.result_types[0][1]})
            elif op.kind in hlo.COLLECTIVE_KINDS:
                coll_by_kind[op.kind] = coll_by_kind.get(op.kind, 0) + 1
                if op.in_while or in_while_func:
                    coll_in_while += 1
            elif op.kind in copies:
                copies[op.kind] += 1
            if op.kind == "custom_call":
                tgt = op.target or "?"
                cc_targets[tgt] = cc_targets.get(tgt, 0) + 1
                if tgt == "tpu_custom_call" and op.payload is not None:
                    pallas_payloads.append(_short_hash(op.payload))
                    pallas_normalized.append(
                        _short_hash(hlo.normalize_payload(op.payload)))

    return {
        "ok": True,
        "error": None,
        "n_funcs": len(reach),
        "params": params,
        "outputs": outputs,
        "f32_dot_conv": f32_dot_conv,
        "launder": launder,
        "collectives": {"by_kind": coll_by_kind, "in_while": coll_in_while},
        "copies": copies,
        "custom_calls": {"targets": cc_targets,
                         "payloads": pallas_payloads,
                         "normalized": pallas_normalized},
    }


def donation_gaps(facts: dict) -> list:
    """Undonated candidate params of one program: float, above the
    bytes floor, shape/dtype-matching some output, not aliased and not
    a declared donor."""
    if not facts.get("ok"):
        return []
    out_shapes = {(tuple(o["dims"] or ()), o["dtype"])
                  for o in facts["outputs"]}
    gaps = []
    for p in facts["params"]:
        if p["dtype"] not in _FLOAT or p["bytes"] < DONATION_BYTES_FLOOR:
            continue
        if p["aliased"] or p["donor"]:
            continue
        if (tuple(p["dims"] or ()), p["dtype"]) in out_shapes:
            gaps.append(p)
    return gaps


def donation_counts(facts: dict) -> dict:
    """Census row: candidates (big float params matching an output) /
    donated (aliased or donor) / gaps."""
    if not facts.get("ok"):
        return {"candidates": 0, "donated": 0, "gaps": 0}
    out_shapes = {(tuple(o["dims"] or ()), o["dtype"])
                  for o in facts["outputs"]}
    cand = don = 0
    for p in facts["params"]:
        if p["dtype"] not in _FLOAT or p["bytes"] < DONATION_BYTES_FLOOR:
            continue
        if (tuple(p["dims"] or ()), p["dtype"]) not in out_shapes:
            continue
        cand += 1
        if p["aliased"] or p["donor"]:
            don += 1
    return {"candidates": cand, "donated": don, "gaps": cand - don}


def entry_census(facts_by_prog: dict) -> dict:
    """Aggregate per-program facts into the entry's structural census —
    the exact record a golden pins."""
    donation = {"candidates": 0, "donated": 0, "gaps": 0}
    precision = {"f32_dot_conv": 0, "launder_chains": 0}
    by_kind: dict = {}
    in_while = 0
    copies = {"copy": 0, "transpose": 0}
    targets: dict = {}
    payloads: list = []
    normalized: list = []
    total_cc = 0
    parse_errors = 0
    for _prog, f in sorted(facts_by_prog.items()):
        if not f.get("ok"):
            parse_errors += 1
            continue
        d = donation_counts(f)
        for k in donation:
            donation[k] += d[k]
        precision["f32_dot_conv"] += len(f["f32_dot_conv"])
        precision["launder_chains"] += len(f["launder"])
        for k, v in f["collectives"]["by_kind"].items():
            by_kind[k] = by_kind.get(k, 0) + v
        in_while += f["collectives"]["in_while"]
        for k in copies:
            copies[k] += f["copies"][k]
        for k, v in f["custom_calls"]["targets"].items():
            targets[k] = targets.get(k, 0) + v
        payloads.extend(f["custom_calls"]["payloads"])
        normalized.extend(f["custom_calls"]["normalized"])
        total_cc += sum(f["custom_calls"]["targets"].values())
    return {
        "donation": donation,
        "precision": precision,
        "collectives": {"total": sum(by_kind.values()),
                        "in_while": in_while,
                        "by_kind": dict(sorted(by_kind.items()))},
        "copies": copies,
        "custom_calls": {"total": total_cc,
                         "pallas_total": len(payloads),
                         "pallas_unique": len(set(payloads)),
                         "pallas_unique_normalized": len(set(normalized)),
                         "targets": dict(sorted(targets.items()))},
        "programs": len(facts_by_prog),
        "parse_errors": parse_errors,
    }


def pattern_findings(entry: str, meta: dict, facts_by_prog: dict) -> list:
    """Per-site diagnostics: (rule, severity, message) triples."""
    out = []
    policy = (meta or {}).get("precision")
    for prog, f in sorted(facts_by_prog.items()):
        if not f.get("ok"):
            out.append(("hlo-structure", "warning",
                        f"{prog}: HLO parse skipped: {f.get('error')}"))
            continue
        for p in donation_gaps(f):
            dims = "x".join(str(d) for d in (p["dims"] or ()))
            out.append((
                "donation-gap", "error",
                f"{prog}: param %arg{p['index']} "
                f"{p['dtype']}[{dims}] ({p['bytes'] // 1024} KiB) "
                f"matches an output shape but is not donated"))
        if policy in ("bf16", "int8"):
            for d in f["f32_dot_conv"]:
                out.append((
                    "precision-leak", "error",
                    f"{prog}: f32 {d['kind']} in {policy}-policy entry "
                    f"(func @{d['func']} line {d['line']})"))
            for ch in f["launder"]:
                out.append((
                    "precision-leak", "error",
                    f"{prog}: convert chain {ch['src']}->f32->{ch['dst']} "
                    f"launders quantized values through f32 "
                    f"(func @{ch['func']} line {ch['line']})"))
        # collectives inside while bodies serialize every iteration on
        # the slowest device — flag each kind once per program
        if f["collectives"]["in_while"]:
            out.append((
                "collective-schedule", "error",
                f"{prog}: {f['collectives']['in_while']} collective(s) "
                f"inside while bodies"))
    return out


def census_findings(entry: str, golden_census: dict, census: dict) -> list:
    """Leaf-for-leaf census diff vs the committed golden.  Both
    directions fail — a regression AND a stale golden (the costguard
    ratchet discipline)."""
    _SECTION_RULE = {
        "donation": "donation-gap", "precision": "precision-leak",
        "collectives": "collective-schedule", "copies": "copy-churn",
        "custom_calls": "custom-call-census",
    }

    def leaves(prefix, d):
        for k, v in d.items():
            p = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, dict):
                yield from leaves(p, v)
            else:
                yield p, v

    gold = dict(leaves("", golden_census))
    now = dict(leaves("", census))
    out = []
    for path in sorted(set(gold) | set(now)):
        g, n = gold.get(path, 0), now.get(path, 0)
        if g == n:
            continue
        rule = _SECTION_RULE.get(path.split(".")[0], "hlo-structure")
        msg = (f"{entry}: {path} changed: golden {g} -> now {n} "
               f"(regen tests/goldens/hloguard/ if intended)")
        if (path.startswith("collectives.by_kind.all_reduce") and n > g
                and golden_census.get("collectives", {})
                                 .get("by_kind", {}).get("all_to_all")):
            msg = (f"{entry}: {path} {g} -> {n}: all-reduce introduced "
                   f"where the golden pins the quantized "
                   f"all_to_all->all_gather two-phase exchange")
        out.append((rule, "error", msg))
    return out
