"""StableHLO text parser for hloguard (docs/analysis.md "Structural
HLO lint").

Operates on the *lowered* module text — ``jax.jit(fn).lower(...)
.as_text()`` on the costguard CPU bring-up, or ``jax.export.export(...,
platforms=["tpu"])(...).mlir_module()`` for the Pallas surfaces — NOT
on the post-compile optimized HLO.  Lowered text preserves user dtypes
(the CPU backend's bf16 emulation converts only appear after XLA
compilation, which would make every bf16 entry look like an f32 leak),
carries donation as ``tf.aliasing_output`` / ``jax.buffer_donor``
parameter attributes, and is the same format for CPU lowerings and TPU
exports, so one parser covers the whole surface.

The parser is deliberately structural, not a full MLIR grammar: it
tracks brace depth (quote-aware — Mosaic ``backend_config`` payloads
embed braces inside string literals), splits the module into functions,
and extracts per-function facts (parameters + donation attrs, result
types, op census, SSA def/use edges for convert-chain walking, while
regions, call edges, custom-call payloads).  Anything it cannot parse
degrades to a ``ParsedModule(ok=False)`` graceful skip rather than an
exception — a malformed module must never wedge the lint gate.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field

# ops the rules care about, by census kind
COLLECTIVE_KINDS = (
    "all_reduce", "all_gather", "all_to_all", "collective_permute",
    "reduce_scatter", "collective_broadcast",
)
_OP_RE = re.compile(
    r'^\s*(?:%[\w#:]+\s*=\s*)?'           # optional "%0 = " / "%2:3 = "
    r'(?:"(?P<q>[\w.]+)"|(?P<u>[\w.]+))'  # "stablehlo.all_reduce" | stablehlo.dot_general
)
_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_ARG_RE = re.compile(r"%arg(\d+):\s*")
_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
_DONOR_RE = re.compile(r"jax\.buffer_donor\s*=\s*true")
_SSA_RE = re.compile(r"%[\w#]+")
_CALLEE_RE = re.compile(r"@([\w.$-]+)")
_BACKEND_CONFIG_STR_RE = re.compile(r'backend_config\s*=\s*"')
# shape digits inside a payload (for the shape-normalized unique count)
_PAYLOAD_SHAPE_RE = re.compile(r"(?:tensor<[^>]*>|\b\d+(?:x\d+)+\b|\b\d+\b)")


@dataclass
class Param:
    index: int
    type: str                 # raw type text, e.g. "tensor<128x784xf32>"
    dims: tuple | None        # (128, 784) for ranked tensors, else None
    dtype: str | None         # "f32", "bf16", "s8", ... else None
    aliased: bool = False     # tf.aliasing_output present
    donor: bool = False       # jax.buffer_donor present


@dataclass
class Op:
    kind: str                 # dialect-stripped name: "dot_general", ...
    line: int                 # 1-based line in the module text
    result: str | None        # first SSA result id ("%12"), if any
    operands: list = field(default_factory=list)   # SSA ids read
    operand_types: list = field(default_factory=list)   # [(dims, dtype)]
    result_types: list = field(default_factory=list)
    in_while: bool = False
    callee: str | None = None   # func.call target
    payload: str | None = None  # custom_call backend_config text
    target: str | None = None   # custom_call target name


@dataclass
class Func:
    name: str
    public: bool
    params: list = field(default_factory=list)     # [Param]
    results: list = field(default_factory=list)    # [(dims, dtype)]
    ops: list = field(default_factory=list)        # [Op]
    defs: dict = field(default_factory=dict)       # ssa id -> Op
    calls_in_while: set = field(default_factory=set)
    calls: set = field(default_factory=set)


@dataclass
class ParsedModule:
    ok: bool
    error: str | None = None
    funcs: dict = field(default_factory=dict)      # name -> Func

    @property
    def main(self):
        if "main" in self.funcs:
            return self.funcs["main"]
        for f in self.funcs.values():
            if f.public:
                return f
        return None


def _brace_delta(line: str) -> int:
    """Net {} depth change, ignoring braces inside string literals."""
    delta, in_str, esc = 0, False, False
    for ch in line:
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch == "{":
            delta += 1
        elif ch == "}":
            delta -= 1
    return delta


def _split_top(text: str, sep: str = ",") -> list:
    """Split on ``sep`` at zero <>/()/{} depth, quote-aware."""
    out, buf, depth, in_str, esc = [], [], 0, False, False
    for ch in text:
        if in_str:
            buf.append(ch)
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch in "<({[":
            depth += 1
        elif ch in ">)}]":
            depth -= 1
        elif ch == sep and depth == 0:
            out.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        out.append("".join(buf))
    return [s.strip() for s in out if s.strip()]


def _tensor_info(type_text: str):
    """("tensor<8x128xf32>") -> ((8, 128), "f32"); None fields if not
    a ranked tensor type."""
    m = _TENSOR_RE.search(type_text)
    if not m:
        return None, None
    parts = m.group(1).split("x")
    dims, dtype = [], None
    for i, p in enumerate(parts):
        if p.isdigit():
            dims.append(int(p))
        else:
            dtype = "x".join(parts[i:])
            break
    else:
        dtype = None
    # strip encodings like "f32, #stablehlo.bounds<...>"
    if dtype:
        dtype = dtype.split(",")[0].strip()
    return tuple(dims), dtype


def _matching_brace(text: str, start: int) -> int:
    """Index just past the brace-balanced region opening at
    ``text[start] == '{'`` (quote-aware); -1 if unbalanced."""
    depth, in_str, esc = 0, False, False
    for i in range(start, len(text)):
        ch = text[i]
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return i + 1
    return -1


def _parse_signature(sig: str, func: Func):
    """Parse a func.func signature: parameters (with donation attrs)
    and result types."""
    lparen = sig.find("(")
    if lparen < 0:
        return
    # walk the parameter list: "%argN: TYPE {attrs}, ..." up to the
    # matching ")" at depth 0
    depth, in_str, esc, i = 0, False, False, lparen
    end = len(sig)
    for i in range(lparen, len(sig)):
        ch = sig[i]
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch in "(<{[":
            depth += 1
        elif ch in ")>}]":
            depth -= 1
            if depth == 0:
                end = i
                break
    params_text = sig[lparen + 1:end]
    for part in _split_top(params_text):
        m = _ARG_RE.match(part)
        if not m:
            continue
        idx = int(m.group(1))
        rest = part[m.end():]
        dims, dtype = _tensor_info(rest)
        p = Param(index=idx, type=rest.strip(), dims=dims, dtype=dtype,
                  aliased=bool(_ALIAS_RE.search(rest)),
                  donor=bool(_DONOR_RE.search(rest)))
        func.params.append(p)
    # result types: after "->", either "(t1, t2, ...)" or a single type
    arrow = sig.find("->", end)
    if arrow < 0:
        return
    res = sig[arrow + 2:].strip()
    if res.startswith("("):
        close = res.rfind(")")
        res_parts = _split_top(res[1:close if close > 0 else len(res)])
    else:
        res_parts = [res]
    for part in res_parts:
        dims, dtype = _tensor_info(part)
        if dtype is not None:
            func.results.append((dims, dtype))


def _parse_op(line: str, line_no: int, in_while: bool):
    m = _OP_RE.match(line)
    if not m:
        return None
    full = m.group("q") or m.group("u")
    if full in ("module", "func.func", "return") or full.startswith("#"):
        return None
    kind = full.split(".")[-1]
    op = Op(kind=kind, line=line_no, result=None, in_while=in_while)
    stripped = line.strip()
    if stripped.startswith("%"):
        op.result = "%" + stripped[1:].split("=")[0].split(":")[0].strip()
    # operands: SSA ids mentioned after the op name, before the trailing
    # functional-type annotation
    body = line[m.end():]
    type_split = body.rfind(" : ")
    op.operands = _SSA_RE.findall(body[:type_split] if type_split >= 0
                                  else body)
    if type_split >= 0:
        types = body[type_split + 3:]
        arrow = types.find("->")
        if arrow >= 0:
            in_t, out_t = types[:arrow], types[arrow + 2:]
        else:
            in_t, out_t = types, types   # "same-type" ops: add, etc.
        op.operand_types = [_tensor_info(t)
                            for t in _split_top(in_t.strip().strip("()"))]
        op.result_types = [_tensor_info(t)
                           for t in _split_top(out_t.strip().strip("()"))]
    if kind == "call":
        cm = _CALLEE_RE.search(body)
        op.callee = cm.group(1) if cm else None
    if kind == "custom_call":
        tm = _CALLEE_RE.search(body)
        if tm:
            op.target = tm.group(1)
        else:
            ct = re.search(r'call_target_name\s*=\s*"([^"]*)"', line)
            op.target = ct.group(1) if ct else None
        bm = _BACKEND_CONFIG_STR_RE.search(line)
        if bm:
            # quote-aware scan of the string literal
            i, esc, buf = bm.end(), False, []
            while i < len(line):
                ch = line[i]
                if esc:
                    buf.append(ch)
                    esc = False
                elif ch == "\\":
                    esc = True
                elif ch == '"':
                    break
                else:
                    buf.append(ch)
                i += 1
            op.payload = "".join(buf)
        else:
            bd = re.search(r"backend_config\s*=\s*\{", line)
            if bd:
                close = _matching_brace(line, bd.end() - 1)
                if close > 0:
                    op.payload = line[bd.end() - 1:close]
    return op


def parse_module(text: str) -> ParsedModule:
    """Parse StableHLO module text into per-function facts.  Never
    raises: malformed input returns ``ParsedModule(ok=False, error=…)``
    so callers can graceful-skip."""
    try:
        return _parse_module(text)
    except Exception as e:  # noqa: BLE001 — graceful-skip contract
        return ParsedModule(ok=False, error=f"{type(e).__name__}: {e}")


def _parse_module(text: str) -> ParsedModule:
    mod = ParsedModule(ok=True)
    lines = text.splitlines()
    depth = 0
    func = None
    func_depth = 0
    sig_buf = None            # accumulating a signature across lines
    while_stack = []          # depths at which a while region opened
    for ln, line in enumerate(lines, start=1):
        delta = _brace_delta(line)
        if sig_buf is not None:
            sig_buf.append(line)
            if depth + delta > depth0_sig:
                _parse_signature(" ".join(sig_buf), func)
                sig_buf = None
            depth += delta
            continue
        stripped = line.strip()
        if stripped.startswith("func.func"):
            name_m = re.search(r"@([\w.$-]+)", line)
            func = Func(name=name_m.group(1) if name_m else f"?line{ln}",
                        public="private" not in stripped.split("@")[0])
            mod.funcs[func.name] = func
            func_depth = depth
            if delta > 0:
                _parse_signature(line, func)
            else:
                sig_buf = [line]
                depth0_sig = depth
            depth += delta
            continue
        if func is not None:
            in_while = bool(while_stack)
            op = _parse_op(line, ln, in_while)
            if op is not None:
                func.ops.append(op)
                if op.result:
                    func.defs[op.result] = op
                if op.kind == "while" and delta > 0:
                    while_stack.append(depth)
                if op.kind == "call" and op.callee:
                    func.calls.add(op.callee)
                    if in_while:
                        func.calls_in_while.add(op.callee)
        depth += delta
        while while_stack and depth <= while_stack[-1]:
            while_stack.pop()
        if func is not None and depth <= func_depth:
            func = None
    if depth != 0:
        return ParsedModule(
            ok=False, error=f"unbalanced braces (depth {depth} at EOF)",
            funcs=mod.funcs)
    if not mod.funcs:
        return ParsedModule(ok=False, error="no func.func found")
    return mod


def reachable_funcs(mod: ParsedModule, entry: str = None) -> set:
    """Names of funcs reachable from ``entry`` (default: main) through
    ``func.call`` edges, entry included."""
    start = entry or (mod.main.name if mod.main else None)
    if start is None or start not in mod.funcs:
        return set()
    seen, todo = set(), [start]
    while todo:
        name = todo.pop()
        if name in seen or name not in mod.funcs:
            continue
        seen.add(name)
        todo.extend(mod.funcs[name].calls)
    return seen


def funcs_reached_from_while(mod: ParsedModule) -> set:
    """Funcs whose bodies execute inside *some* while region reachable
    from main: callees of in-while ``func.call`` sites, transitively
    (a fori_loop body lowers to a private func called from the while
    region, so "collective inside a while" must follow call edges)."""
    reach = reachable_funcs(mod)
    seeds = set()
    for name in reach:
        seeds |= mod.funcs[name].calls_in_while
    seen, todo = set(), list(seeds)
    while todo:
        name = todo.pop()
        if name in seen or name not in mod.funcs:
            continue
        seen.add(name)
        todo.extend(mod.funcs[name].calls)
    return seen


def trace_back(func: Func, op: Op, want, limit: int = 256, stop=None):
    """Walk SSA operands backwards from ``op`` within ``func`` looking
    for an op for which ``want(op)`` is true; returns it or None.
    ``stop(op)`` true = do not walk through that op's operands (a
    barrier).  Bounded so pathological graphs stay cheap."""
    seen, todo, steps = set(), list(op.operands), 0
    while todo and steps < limit:
        ssa = todo.pop()
        if ssa in seen:
            continue
        seen.add(ssa)
        steps += 1
        d = func.defs.get(ssa)
        if d is None:
            continue
        if want(d):
            return d
        if stop is not None and stop(d):
            continue
        todo.extend(d.operands)
    return None


def normalize_payload(payload: str) -> str:
    """Shape-normalized payload: shape/tensor tokens stripped, so two
    instantiations of one kernel at different geometries dedupe (the
    item-4 "same kernel, 150 shapes" signal)."""
    return _PAYLOAD_SHAPE_RE.sub("#", payload)
