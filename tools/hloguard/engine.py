"""hloguard engine: surface → facts (cached) → findings vs goldens.

The run contract mirrors costguard's ``budget.run_check``:

* every selected surface is lowered fresh (lowering is cheap and the
  text hash is the soundness anchor), the expensive parse/extract step
  is memoized in ``.hloguard_cache/`` keyed on the lowered text, and
  the rules run over facts every time;
* a surface gates only when its golden's recorded backend/device-count
  environment matches (CPU-vs-TPU lowering differs structurally — a
  golden from one bring-up must not fail the other);
* both directions fail: an unsuppressed finding AND a stale golden /
  stale suppression — the audited surface stays audited.

Suppressions live in the golden (``suppressions: [{rule, match,
justification}]``), matched by rule id + message substring, and the
justification is REQUIRED: an empty one raises ``bad-suppression``,
which cannot itself be suppressed (the mxlint contract).
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import List, Optional

from tools.analysis.core import Finding

from . import surfaces
from .rules import (REPORT_VERSION, RULES, census_findings, entry_census,
                    extract_facts, pattern_findings)

GOLDEN_SUBDIR = "tests/goldens/hloguard"
CACHE_DIR_NAME = ".hloguard_cache"


def golden_path(name: str, root) -> Path:
    return Path(root) / GOLDEN_SUBDIR / f"{name}.json"


def load_golden(name: str, root) -> Optional[dict]:
    p = golden_path(name, root)
    if not p.exists():
        return None
    return json.loads(p.read_text(encoding="utf-8"))


def environment() -> dict:
    import jax
    return {"backend": jax.default_backend(),
            "n_devices": jax.device_count(),
            "jax_version": jax.__version__,
            "report_version": REPORT_VERSION}


def _cache(root, cache_dir):
    import jax

    from tools.analysis.cache import FileCache
    sig = (f"hloguard-{REPORT_VERSION}-jax{jax.__version__}-"
           f"{jax.default_backend()}-{jax.device_count()}d")
    return FileCache(Path(root), cache_dir or Path(root) / CACHE_DIR_NAME,
                     signature=sig)


def facts_for_programs(programs, root=None, use_cache: bool = False,
                       cache_dir=None) -> dict:
    """{program name: facts} with the HLO-hash cache in front of the
    parse/extract step — the costguard ``report_for_programs`` pattern
    one compile earlier (nothing here ever invokes XLA)."""
    cache = _cache(root, cache_dir) if use_cache and root is not None \
        else None
    out = {}
    for prog_name, text in programs:
        key = rec = None
        if cache is not None:
            key = cache.key(prog_name, text.encode("utf-8"))
            rec = cache.get(prog_name, key)
        if rec is not None:
            out[prog_name] = rec["facts"]
            continue
        f = extract_facts(text)
        out[prog_name] = f
        if cache is not None:
            cache.put(prog_name, key, {"relpath": prog_name, "facts": f})
    return out


@dataclasses.dataclass
class EntryResult:
    name: str
    census: Optional[dict] = None
    findings: List[Finding] = dataclasses.field(default_factory=list)
    golden: Optional[dict] = None
    gated: bool = True        # False = golden from another environment

    @property
    def ok(self) -> bool:
        return not any(f.severity == "error" and not f.suppressed
                       for f in self.findings)

    def to_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok, "gated": self.gated,
                "census": self.census,
                "findings": [f.to_dict() for f in self.findings]}


@dataclasses.dataclass
class CheckResult:
    entries: List[EntryResult]
    extra_findings: List[Finding] = dataclasses.field(default_factory=list)

    @property
    def findings(self) -> List[Finding]:
        out = [f for e in self.entries for f in e.findings]
        out.extend(self.extra_findings)
        return out

    @property
    def ok(self) -> bool:
        return (all(e.ok for e in self.entries)
                and not any(f.severity == "error" and not f.suppressed
                            for f in self.extra_findings))

    def to_json(self) -> str:
        return json.dumps(
            {"ok": self.ok, "report_version": REPORT_VERSION,
             "entries": [e.to_dict() for e in self.entries],
             "extra_findings": [f.to_dict()
                                for f in self.extra_findings]},
            indent=2, sort_keys=True)

    def to_sarif(self) -> str:
        from tools.analysis.sarif import to_sarif
        return to_sarif(sorted(self.findings,
                               key=lambda f: (f.path, f.line, f.rule,
                                              f.message)),
                        rules=sarif_rules(), tool_version=REPORT_VERSION,
                        tool_name="hloguard")

    def render(self) -> str:
        lines = []
        for e in self.entries:
            n_sup = sum(1 for f in e.findings if f.suppressed)
            tag = "ok" if e.ok else "FAIL"
            if not e.gated:
                tag += " (not gated: golden from another environment)"
            extra = f", {n_sup} suppressed" if n_sup else ""
            lines.append(f"{e.name:28s} {tag}{extra}")
            for f in e.findings:
                if not f.suppressed:
                    lines.append(f"  {f.render()}")
        for f in self.extra_findings:
            lines.append(f.render())
        n_bad = sum(1 for f in self.findings
                    if f.severity == "error" and not f.suppressed)
        lines.append(f"hloguard: {len(self.entries)} surface(s), "
                     f"{n_bad} unsuppressed finding(s): "
                     f"{'OK' if self.ok else 'FAIL'}")
        return "\n".join(lines)


class _SarifRule:
    def __init__(self, rid, desc):
        self.id = rid
        self.description = desc
        self.default_severity = ("warning"
                                 if rid == "stale-suppression" else "error")


def sarif_rules():
    return [_SarifRule(rid, desc) for rid, desc in sorted(RULES.items())]


def _finding(rule, severity, message, path, line=1) -> Finding:
    return Finding(rule=rule, path=path, line=line, col=1,
                   message=message, severity=severity)


def _relpath(name: str, root) -> str:
    src = surfaces.source_of(name)
    try:
        return src.resolve().relative_to(Path(root).resolve()).as_posix()
    except ValueError:
        return src.as_posix()


def _apply_suppressions(found: List[Finding], golden: Optional[dict],
                        entry: str, path: str) -> List[Finding]:
    """Golden suppressions over findings, with the mxlint contract:
    justification required, unused suppressions flagged, and the
    suppression checker itself unsuppressible."""
    sups = (golden or {}).get("suppressions") or []
    used = [False] * len(sups)
    out = []
    for f in found:
        for i, s in enumerate(sups):
            if s.get("rule") != f.rule:
                continue
            if s.get("match", "") not in f.message:
                continue
            if not (s.get("justification") or "").strip():
                # matched but unjustified: the finding stays live AND
                # the suppression itself is a finding
                continue
            f.suppressed = True
            f.justification = s["justification"]
            used[i] = True
            break
        out.append(f)
    for i, s in enumerate(sups):
        if not (s.get("justification") or "").strip():
            out.append(_finding(
                "bad-suppression", "error",
                f"{entry}: suppression for rule {s.get('rule')!r} "
                f"(match {s.get('match', '')!r}) has no justification — "
                f"write down WHY or delete it", path))
        elif not used[i]:
            out.append(_finding(
                "stale-suppression", "warning",
                f"{entry}: suppression for rule {s.get('rule')!r} "
                f"(match {s.get('match', '')!r}) matched no finding — "
                f"delete it or fix its match string", path))
    return out


def check_entry(name: str, root, use_cache: bool = False,
                cache_dir=None) -> EntryResult:
    """Lower one surface and judge its structure against the golden.
    Never compiles, never executes a step."""
    res = EntryResult(name=name)
    path = _relpath(name, root)
    surface = surfaces.build(name)
    facts = facts_for_programs(surface.programs, root=root,
                               use_cache=use_cache, cache_dir=cache_dir)
    res.census = entry_census(facts)
    found = [_finding(rule, sev, msg, path)
             for rule, sev, msg in
             pattern_findings(name, surface.meta, facts)]
    golden = load_golden(name, root)
    if golden is None:
        found.append(_finding(
            "missing-golden", "error",
            f"{name}: no structural golden at {golden_path(name, root)} "
            f"— tests/goldens/hloguard/regen_hloguard.py writes one",
            path))
        res.findings = found
        return res
    res.golden = golden
    env = environment()
    if golden.get("report_version") != REPORT_VERSION:
        found.append(_finding(
            "hlo-structure", "error",
            f"{name}: golden schema {golden.get('report_version')!r} != "
            f"analyzer schema {REPORT_VERSION!r} — regenerate", path))
        res.findings = found
        return res
    if (golden.get("backend"), golden.get("n_devices")) != \
            (env["backend"], env["n_devices"]):
        res.gated = False     # audit-only: lowerings are not comparable
        res.findings = _apply_suppressions(found, golden, name, path)
        return res
    found.extend(_finding(rule, sev, msg, path)
                 for rule, sev, msg in
                 census_findings(name, golden.get("census") or {},
                                 res.census))
    res.findings = _apply_suppressions(found, golden, name, path)
    return res


def run_check(entries=None, root=None, use_cache: bool = False,
              cache_dir=None) -> CheckResult:
    """The whole structural audit: every selected surface against its
    golden, plus the selection-independent reverse check (goldens whose
    surface is gone)."""
    root = Path(root) if root is not None else Path.cwd()
    selected = surfaces.names() if entries is None else list(entries)
    results = [check_entry(n, root, use_cache=use_cache,
                           cache_dir=cache_dir) for n in selected]
    extra = []
    gdir = root / GOLDEN_SUBDIR
    if gdir.is_dir():
        registered = set(surfaces.names())
        for p in sorted(gdir.glob("*.json")):
            if p.stem not in registered:
                extra.append(_finding(
                    "stale-golden", "error",
                    f"{p.stem}: structural golden committed but no such "
                    f"surface is registered — delete "
                    f"{GOLDEN_SUBDIR}/{p.name} or restore the surface",
                    f"{GOLDEN_SUBDIR}/{p.name}"))
    return CheckResult(entries=results, extra_findings=extra)
