"""hloguard CLI: ``python -m tools.hloguard [target ...]``.

Exit code 0 = every selected surface structurally clean (0 unsuppressed
findings, no stale goldens), 1 = findings / drift / missing golden,
2 = usage.

Targets are surface names, or paths — a path selects every registered
surface whose builder is defined under it (the costguard CLI contract:
``python -m tools.hloguard mxnet_tpu/`` audits the whole registered
surface).  No target = everything.

Environment: forces ``JAX_PLATFORMS=cpu`` with an 8-device virtual mesh
unless the caller already chose a platform — structural goldens record
their bring-up and only *gate* in a matching backend/device-count
environment (the CPU-vs-TPU lowering caveat in docs/analysis.md).
"""
from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path


def _env_bringup():
    """Same pre-jax-import bring-up as tests/conftest.py — must run
    before anything imports jax."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ["JAX_PLATFORMS"] == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.hloguard",
        description="structural lint over lowered HLO "
                    "(docs/analysis.md \"Structural HLO lint\")")
    parser.add_argument("targets", nargs="*", default=[],
                        help="surface names and/or paths (a path selects "
                             "the surfaces defined under it); default: "
                             "every registered surface")
    parser.add_argument("--format", choices=("human", "json", "sarif"),
                        default="human", dest="fmt")
    parser.add_argument("--list", action="store_true",
                        help="list registered surfaces and exit")
    parser.add_argument("--root", default=None,
                        help="repo root for goldens/cache (default: cwd)")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the .hloguard_cache/ facts cache "
                             "(always re-parse)")
    parser.add_argument("--cache-dir", default=None,
                        help="cache directory (default: "
                             "<root>/.hloguard_cache)")
    args = parser.parse_args(argv)

    _env_bringup()
    from . import run_check, surfaces

    if args.list:
        for name in surfaces.names():
            kind = ("tpu-export" if name in surfaces.EXPORT_SURFACES
                    else "entrypoint")
            print(f"{name:28s} {kind}")
        return 0

    root = Path(args.root).resolve() if args.root else Path.cwd()
    known = surfaces.names()
    selected = []
    for t in args.targets:
        if t in known:
            selected.append(t)
            continue
        p = Path(t)
        if p.exists():
            rp = p.resolve()
            hits = [n for n in known if _selects(n, rp, root)]
            selected.extend(h for h in hits if h not in selected)
            if not hits:
                print(f"# note: no registered surface under {t}",
                      file=sys.stderr)
            continue
        parser.error(f"{t!r} is neither a registered surface nor a "
                     f"path (see --list)")
    if args.targets and not selected:
        print("hloguard: no registered surfaces under the given targets "
              "— auditing goldens only", file=sys.stderr)
    result = run_check(entries=selected if args.targets else None,
                       root=root, use_cache=not args.no_cache,
                       cache_dir=args.cache_dir)
    if args.fmt == "json":
        print(result.to_json())
    elif args.fmt == "sarif":
        print(result.to_sarif())
    else:
        print(result.render())
    return 0 if result.ok else 1


def _selects(name: str, path: Path, root: Path) -> bool:
    """Does a path target cover surface ``name``?  Its builder file is
    under the path, or the path contains the mxnet_tpu package (every
    surface audits that package's lowered programs)."""
    from . import surfaces
    if surfaces.source_of(name).resolve().is_relative_to(path):
        return True
    pkg = (root / "mxnet_tpu").resolve()
    return pkg == path or pkg.is_relative_to(path)


if __name__ == "__main__":
    sys.exit(main())
