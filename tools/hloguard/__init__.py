"""hloguard — structural lint over lowered HLO (docs/analysis.md
"Structural HLO lint").

The fourth leg of the static-analysis stack: mxlint reads Python
source, costguard reads compiled-program costs, spmdlint reads
shard_map regions — hloguard reads the *structure* of the lowered
StableHLO itself, where missed donations, precision laundering,
collective schedules, layout churn, and Pallas instantiation blowups
are actually visible (Julia→TPU whole-program compilation,
arXiv:1810.09868).

Gate: ``python -m tools.hloguard`` (exit 0 = 0 unsuppressed findings
over every registered surface with an environment-matched golden).
"""
from .engine import (CheckResult, EntryResult, check_entry, environment,
                     golden_path, load_golden, run_check)
from .rules import REPORT_VERSION, RULES

__all__ = [
    "CheckResult", "EntryResult", "REPORT_VERSION", "RULES",
    "check_entry", "environment", "golden_path", "load_golden",
    "run_check",
]
