"""The audited surface: which lowered modules hloguard lints.

Two kinds of surface, one text format:

* **Entrypoint surfaces** — every registered costguard entry point,
  lowered under the same ``JAX_PLATFORMS=cpu`` bring-up costguard uses
  (zero device steps, zero XLA compiles: hloguard reads the *lowered*
  StableHLO, which is cheaper than costguard's compiled reports and
  preserves user dtypes — the CPU backend's bf16-emulation converts
  only appear post-compile and would otherwise make every bf16 entry
  look like an f32 leak).
* **Pallas export surfaces** — the fused norm+relu+conv and ragged
  paged-attention kernels lowered for the REAL TPU platform via
  ``jax.export`` (client-side Mosaic, runs on a CPU host — the
  test_fused_conv_lowering.py pattern).  These carry the
  ``tpu_custom_call`` payloads the custom-call census counts: the
  unique-vs-total instantiation metric ROADMAP item 4's ~150-kernel
  compile blowup needs.

Builds are memoized per process: the hloguard gate, the costguard gate,
and chaos both walk the full surface in one tier-1 run, and lowering is
deterministic, so paying the ~20 s more than once buys nothing.
"""
from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, List, Tuple

#: hloguard-only surfaces (beyond the costguard registry), in gate order
EXPORT_SURFACES = ("pallas_fused_conv_tpu", "pallas_paged_attention_tpu")

_MEMO: Dict[str, "Surface"] = {}


@dataclasses.dataclass
class Surface:
    """One audited name: its program texts and golden metadata."""
    name: str
    meta: dict
    programs: List[Tuple[str, str]]    # [(program name, lowered text)]


def names() -> List[str]:
    from tools.costguard import entrypoints
    return sorted(entrypoints.names()) + list(EXPORT_SURFACES)


def source_of(name: str) -> Path:
    """File a surface's findings anchor to (SARIF locations)."""
    if name in EXPORT_SURFACES:
        return Path(__file__).resolve()
    from tools.costguard import entrypoints
    return entrypoints.source_of(name)


def build(name: str) -> Surface:
    if name not in _MEMO:
        if name == "pallas_fused_conv_tpu":
            _MEMO[name] = _build_fused_conv()
        elif name == "pallas_paged_attention_tpu":
            _MEMO[name] = _build_paged_attention()
        else:
            _MEMO[name] = _build_entrypoint(name)
    return _MEMO[name]


def _build_entrypoint(name: str) -> Surface:
    from tools.costguard import entrypoints
    eb = entrypoints.build(name)
    programs = [(p.name, p.lowered if isinstance(p.lowered, str)
                 else p.lowered.as_text()) for p in eb.programs]
    return Surface(name=name, meta=dict(eb.meta, kind="entrypoint"),
                   programs=programs)


def _export_tpu(fn, *avals) -> str:
    import jax
    # older jax does not auto-import the export submodule (see
    # gluon/block.py): the bare attribute raises until this runs
    from jax import export as _jax_export  # noqa: F401
    return jax.export.export(jax.jit(fn),
                             platforms=["tpu"])(*avals).mlir_module()


def _build_fused_conv() -> Surface:
    """A three-layer fused-conv tower in ONE program: two 3x3 layers at
    the identical geometry plus a 1x1 head.  The census must see
    pallas_unique < pallas_total — the repeated 3x3 instantiation is
    the dedup headroom the ~150-kernel A/B blowup is made of."""
    import jax
    import jax.numpy as jnp

    import mxnet_tpu.ops.pallas.fused_conv as fc

    sds = jax.ShapeDtypeStruct
    x = sds((2, 16, 16, 64), jnp.bfloat16)
    scale = sds((64,), jnp.float32)
    shift = sds((64,), jnp.float32)
    w3 = sds((3, 3, 64, 64), jnp.bfloat16)
    w1 = sds((1, 1, 64, 64), jnp.bfloat16)

    def tower(x, scale, shift, wa, wb, wh):
        # the repeated layers run through ONE call site, the model-zoo
        # shape (a Python loop over per-layer params): Mosaic payloads
        # embed call-site locations, so same-geometry instantiations
        # dedupe byte-exactly only when the site is shared — exactly
        # how the real ~150-kernel tower would (or would fail to)
        for w in (wa, wb):
            x = fc.norm_relu_conv(x, scale, shift, w, interpret=False)
        return fc.norm_relu_conv(x, scale, shift, wh, interpret=False)

    text = _export_tpu(tower, x, scale, shift, w3, w3, w1)
    meta = {"kind": "export", "platforms": ["tpu"], "precision": "bf16",
            "model": "fused norm+relu+conv tower 3x3/3x3/1x1",
            "geometry": "x bf16[2,16,16,64], 64ch"}
    return Surface(name="pallas_fused_conv_tpu", meta=meta,
                   programs=[("pallas_fused_conv_tpu/tower", text)])


def _build_paged_attention() -> Surface:
    """The ragged paged-attention decode kernel at the llm decode-grid
    geometry (8 slots, 8h x 4d — the ``_llm_parts`` head layout) and at
    a second, larger-page geometry: two distinct Mosaic instantiations
    of ONE kernel, so the census pins total 2 / unique 2 and any
    accidental re-instantiation at an existing geometry shows up as
    total moving without unique."""
    import functools

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas)

    sds = jax.ShapeDtypeStruct
    programs = []
    for tag, (slots, pages_per_seq, page_size, heads, head_dim) in (
            ("decode_8h4", (8, 16, 16, 8, 4)),
            ("decode_8h32", (8, 4, 32, 8, 32))):
        n_pages = slots * pages_per_seq
        q = sds((slots, heads, head_dim), jnp.float32)
        pages = sds((n_pages, page_size, heads, head_dim), jnp.float32)
        tables = sds((slots, pages_per_seq), jnp.int32)
        lengths = sds((slots,), jnp.int32)
        fn = functools.partial(paged_decode_attention_pallas,
                               interpret=False)
        text = _export_tpu(fn, q, pages, pages, tables, lengths)
        programs.append((f"pallas_paged_attention_tpu/{tag}", text))
    meta = {"kind": "export", "platforms": ["tpu"], "precision": "f32",
            "model": "ragged paged decode attention "
                     "(ops/pallas/paged_attention.py)"}
    return Surface(name="pallas_paged_attention_tpu", meta=meta,
                   programs=programs)
